"""mxlint flow-sensitive rules: resource-leak, thread-lifecycle,
blocking-under-lock.

Pass 2 rules that consume the :mod:`.cfg` layer.  Each subscribes to
``FunctionDef`` and analyzes top-level functions/methods only (the
walker fires *before* the function is pushed, so an empty
``ctx.func_stack`` means "this def is the top-level one"); nested defs
run on some other frame's path and are skipped, in mxlint's usual
missed-finding-over-false-finding direction.

All three share one CFG per function (cached on the ``FileContext``),
and all three attach ``Finding.hops`` — the actual ``file:line``
program-point path that exhibits the defect — because a flow-sensitive
verdict the reader cannot replay is indistinguishable from a false
positive.

What makes the leak search precise enough to run over this repo clean
(every suppression below earned by a real near-miss in serving/):

- **Release beats raise**: a ``release()`` call closes the path before
  its own exception edge is considered — cleanup that throws is the
  cleanup's bug, not this acquire's.
- **Transfer after raise**: a call that receives the resource closes
  the path only if it *completes*; its exception edge is explored with
  the obligation still open.  This is exactly the shape of the real
  span leaks this PR fixes: ``submit(req)`` raising ``ServerOverloaded``
  did not take ownership of ``req.trace``.
- **None-guard correlation**: on the arm of ``if table is None:`` the
  resource provably does not exist, so the path is pruned — the
  ``reserve() -> if None -> break`` admission loop is clean, not a leak.
- **Proxy bindings**: ``req.trace = tracer().begin(...)`` binds the
  obligation to ``req`` (the local carrier), while ``self.x = acquire()``
  transfers it to the instance at birth.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from . import protocols as _p
from .cfg import CFG, MAY_RAISE as _MAY_RAISE, build_cfg, leak_path
from .core import FUNC_TYPES, FileContext, Finding, Rule, _lock_token

__all__ = ["ResourceLeakRule", "ThreadLifecycleRule",
           "BlockingUnderLockRule"]


def _names(expr: Optional[ast.AST]) -> Set[str]:
    if expr is None:
        return set()
    return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}


def _contains(root: ast.AST, target: ast.AST) -> bool:
    return any(n is target for n in ast.walk(root))


#: calls that cannot meaningfully raise — treating them as raise sites
#: would make every path between an acquire and its release "leaky via
#: len()", drowning the real exit-path findings
_INFALLIBLE_NAMES = frozenset(("len", "type", "id", "isinstance",
                               "sorted", "min", "max"))
_INFALLIBLE_METHODS = frozenset(("monotonic", "perf_counter", "time",
                                 "get_ident", "append", "items",
                                 "values", "keys", "get"))

#: transfer verbs that either succeed or the process is already lost —
#: a container insert does not need its exception edge explored the way
#: an admission ``submit()`` (which raises BY DESIGN) does
_INFALLIBLE_TRANSFER = frozenset(("append", "appendleft", "add",
                                  "insert", "register",
                                  "_register_atexit"))


def _infallible(call: ast.Call) -> bool:
    recv, meth = _p.call_desc(call)
    if not recv:
        return meth in _INFALLIBLE_NAMES
    return meth in _INFALLIBLE_METHODS


class _Scan:
    """One shared lexical pass per top-level function: which flow rules
    have any business building a CFG here?  Most functions touch no
    protocol resource, thread, or lock — they skip the whole tier."""

    __slots__ = ("acquire", "thread", "locks", "withitems")

    def __init__(self) -> None:
        self.acquire = False
        self.thread = False
        self.locks = False
        self.withitems: Dict[int, ast.withitem] = {}


class _FlowRule(Rule):
    """Shared plumbing: per-function dispatch + CFG cache + hop strings."""

    interests = FUNC_TYPES

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        if ctx.func_stack:            # nested def: enclosing frame's path
            return
        self.check_func(node, ctx)

    def check_func(self, func: ast.AST, ctx: FileContext) -> None:
        raise NotImplementedError

    @staticmethod
    def _scan(func: ast.AST, ctx: FileContext) -> _Scan:
        cache = getattr(ctx, "_flow_scan", None)
        if cache is None:
            cache = ctx._flow_scan = {}
        sc = cache.get(id(func))
        if sc is not None:
            return sc
        sc = cache[id(func)] = _Scan()
        for n in ast.walk(func):
            if isinstance(n, (ast.With, ast.AsyncWith)):
                for item in n.items:
                    if not sc.locks and \
                            _lock_token(item.context_expr) is not None:
                        sc.locks = True
                    for c in ast.walk(item.context_expr):
                        if isinstance(c, ast.Call):
                            sc.withitems[id(c)] = item
            elif isinstance(n, ast.Call):
                if not sc.acquire and _p.match_acquire(n) is not None:
                    sc.acquire = True
                if not sc.thread and (_p.is_thread_ctor(n) or
                                      _p.thread_start(n)):
                    sc.thread = True
                if not sc.locks and isinstance(n.func, ast.Attribute) \
                        and n.func.attr == "acquire" \
                        and _lock_token(n.func.value) is not None:
                    sc.locks = True
        return sc

    @staticmethod
    def _cfg(func: ast.AST, ctx: FileContext) -> CFG:
        cache = getattr(ctx, "_cfg_cache", None)
        if cache is None:
            cache = ctx._cfg_cache = {}
        g = cache.get(id(func))
        if g is None:
            g = cache[id(func)] = build_cfg(func)
        return g

    @staticmethod
    def _symbol(func: ast.AST, ctx: FileContext) -> str:
        if ctx.class_stack:
            return f"{ctx.class_stack[-1].name}.{func.name}"
        return func.name

    @staticmethod
    def _hops(cfg: CFG, path, relpath: str,
              lead_line: int = 0) -> Tuple[str, ...]:
        """``lead_line`` seeds the list with the acquire/start site —
        the path itself begins just AFTER that event, and when it is the
        last event of its block (acquire-then-fall-off-the-end) the walk
        crosses no further events at all; every flow finding still owes
        the reader at least the one line the obligation was born on."""
        out: List[str] = []
        last = None
        if lead_line:
            out.append(f"{relpath}:{lead_line}")
            last = lead_line
        for bid, idx in path:
            blk = cfg.block(bid)
            if idx < len(blk.events):
                ln = blk.events[idx].line
                if ln and ln != last:
                    out.append(f"{relpath}:{ln}")
                    last = ln
        return tuple(out)


def _guard_name(e: ast.expr) -> Optional[str]:
    if isinstance(e, ast.Name):
        return e.id
    if isinstance(e, ast.Attribute):      # `req.trace is not None`
        return _p._expr_text(e)
    return None


def _none_guard(test: ast.expr) -> Optional[Tuple[str, bool]]:
    """(name, absent_arm_is_true) when ``test`` is a presence guard on
    ``name``: ``x is None`` → (x, True); ``x is not None`` / ``x`` →
    (x, False); ``not x`` → (x, True).  ``name`` may be a dotted
    attribute path (``req.trace``) — pruning only ever applies when it
    matches a bound/twin name, so arbitrary truthiness tests stay
    inert."""
    if isinstance(test, ast.Compare) and len(test.ops) == 1 and \
            isinstance(test.comparators[0], ast.Constant) and \
            test.comparators[0].value is None:
        nm = _guard_name(test.left)
        if nm is not None:
            if isinstance(test.ops[0], ast.Is):
                return nm, True
            if isinstance(test.ops[0], ast.IsNot):
                return nm, False
    nm = _guard_name(test)
    if nm is not None:
        return nm, False
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        nm = _guard_name(test.operand)
        if nm is not None:
            return nm, True
    return None


class ResourceLeakRule(_FlowRule):
    name = "resource-leak"
    description = ("a path from a protocol acquire (KV block, span, "
                   "tmp file, ContextVar token, ...) to a function exit "
                   "— exception edges included — crosses no release or "
                   "ownership transfer")

    def check_func(self, func: ast.AST, ctx: FileContext) -> None:
        sc = self._scan(func, ctx)
        if not sc.acquire:
            return
        cfg = self._cfg(func, ctx)
        withitems = sc.withitems
        acquires = []
        all_bound: Set[str] = set()
        for bid, idx, ev in cfg.events():
            if ev.kind != "call":
                continue
            proto = _p.match_acquire(ev.node)
            if proto is None:
                continue
            item = withitems.get(id(ev.node))
            if item is not None and proto.ctx_managed:
                continue              # `with tracer().begin(...):` — safe
            binding = self._binding(cfg, bid, idx, ev.node, item, proto)
            if binding is None:
                continue              # owner holds it from birth
            acquires.append((bid, idx, ev, proto, binding))
            all_bound |= binding[0]
        for bid, idx, ev, proto, (bound, twins) in acquires:
            self._search(cfg, (bid, idx), ev, proto, bound, twins,
                         all_bound, ctx, self._symbol(func, ctx))

    @staticmethod
    def _binding(cfg: CFG, bid: int, idx: int, call: ast.Call,
                 item: Optional[ast.withitem], proto: _p.Protocol
                 ) -> Optional[Tuple[Set[str], Dict[str, bool]]]:
        """(names carrying the obligation, twin guards), with an empty
        name set for an unbound acquire — or None when ownership
        transfers at the binding site itself (``self.x = acquire()`` /
        ``d[k] = acquire()``).

        Twin guards handle conditional binders: for ``rb = None if sp
        is None else begin(...)`` the resource provably exists exactly
        when ``sp`` does, so a later ``if sp is None:`` prunes the
        absent arm the same way a direct ``if rb is None:`` would.
        Each entry maps a twin name to its polarity — True when the
        resource is absent exactly when the twin is None/falsy."""
        blk = cfg.block(bid)
        for later in blk.events[idx + 1:]:
            if later.kind != "assign":
                continue
            n = later.node
            if not _contains(getattr(n, "value", n) or n, call):
                continue
            twins: Dict[str, bool] = {}
            val = getattr(n, "value", None)
            if isinstance(val, ast.IfExp):
                g = _none_guard(val.test)
                if g is not None:
                    nm, absent_if_true = g
                    acquire_on_true = _contains(val.body, call)
                    # resource exists on the arm holding the acquire;
                    # polarity True = absent tracks "nm is None/falsy"
                    twins[nm] = absent_if_true == (not acquire_on_true)
            tgts = n.targets if isinstance(n, ast.Assign) else [n.target]
            for tgt in tgts:
                if isinstance(tgt, ast.Name):
                    return {tgt.id}, twins
                if isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name):
                    if tgt.value.id in ("self", "cls"):
                        return None   # instance owns it from birth
                    # req.trace = begin(): the proxy (req) carries the
                    # obligation for transfer purposes; the dotted path
                    # itself is what presence guards and method calls
                    # name
                    return {tgt.value.id,
                            f"{tgt.value.id}.{tgt.attr}"}, twins
                if isinstance(tgt, (ast.Subscript, ast.Tuple)):
                    return None
            return set(), twins
        if item is not None and isinstance(item.optional_vars, ast.Name):
            return {item.optional_vars.id}, {}
        if proto.needs_binding:
            return None               # fire-and-forget lookalike
        return set(), {}

    def _search(self, cfg: CFG, acq_pt, ev, proto: _p.Protocol,
                bound: Set[str], twins: Dict[str, bool],
                all_bound: Set[str], ctx: FileContext,
                symbol: str) -> None:
        transfers: List[ast.Call] = []

        def on_event(e) -> Optional[str]:
            n, k = e.node, e.kind
            if k == "call":
                if _p.match_release(n, proto):
                    return "close"
                recv, meth = _p.call_desc(n)
                if bound:
                    argv = list(n.args) + [kw.value for kw in n.keywords]
                    if any(bound & _names(a) for a in argv):
                        if meth in _INFALLIBLE_TRANSFER:
                            return "close"
                        transfers.append(n)
                        return "transfer-after-raise"
                if recv in all_bound:
                    # a method of a managed resource (sp.annotate(...))
                    # raising is that resource's bug, not this path's
                    return "noraise"
                if _infallible(n):
                    return "noraise"
            elif k == "assign" and bound:
                if not (bound & _names(getattr(n, "value", None))):
                    return None
                tgts = n.targets if isinstance(n, ast.Assign) \
                    else [n.target]
                for tgt in tgts:
                    if isinstance(tgt, ast.Subscript) or \
                            (isinstance(tgt, ast.Attribute) and
                             isinstance(tgt.value, ast.Name) and
                             tgt.value.id in ("self", "cls")):
                        return "close"
            elif k in ("return", "yield") and bound:
                if bound & _names(getattr(n, "value", None)):
                    return "close"
            return None

        def branch_hint(test, is_true) -> Optional[str]:
            g = _none_guard(test)
            if g is None:
                return None
            nm, absent_if_true = g
            if nm in bound and is_true == absent_if_true:
                return "close"
            if nm in twins:
                absent_arm = absent_if_true if twins[nm] \
                    else not absent_if_true
                if is_true == absent_arm:
                    return "close"
            return None

        path = leak_path(cfg, acq_pt, on_event,
                         branch_hint if (bound or twins) else None)
        if path is None:
            return
        exits_raising = path[-1][0] == cfg.raise_id
        exit_kind = "an exception exit" if exits_raising else \
            "a normal return"
        verbs = "/".join(sorted(proto.release_methods))
        reason = [f"{proto.name} acquire at {ctx.relpath}:{ev.line}"]
        # was the last thing on the path a would-be transfer that raised?
        if exits_raising and len(path) >= 2:
            pb, pi = path[-2]
            pblk = cfg.block(pb)
            if pi < len(pblk.events) and \
                    any(pblk.events[pi].node is t for t in transfers):
                reason.append(
                    f"callee at line {pblk.events[pi].line} raised "
                    "before taking ownership")
                evidence = self._transfer_evidence(
                    ctx, symbol, pblk.events[pi].node, proto)
                if evidence:
                    reason.append(evidence)
        reason.append(f"reaches {exit_kind} with no {verbs} and no "
                      "ownership transfer")
        reason.append(f"fix: {proto.hint}")
        ctx.report(self, ev.line,
                   f"{proto.resource} can leak: a path reaches "
                   f"{exit_kind} without {verbs}",
                   symbol=symbol, reason=tuple(reason),
                   hops=self._hops(cfg, path, ctx.relpath,
                                   lead_line=ev.line))

    @staticmethod
    def _transfer_evidence(ctx: FileContext, symbol: str,
                           call: ast.Call,
                           proto: _p.Protocol) -> Optional[str]:
        """Interprocedural color for a transfer-that-raised: resolve the
        callee through the PR-6 call graph and cite where its chain
        performs (or provably does not perform) the protocol release."""
        proj = ctx.project
        if proj is None:
            return None
        ff = proj.functions.get(f"{ctx.relpath}::{symbol}")
        if ff is None:
            return None
        fn = call.func
        if isinstance(fn, ast.Name):
            desc = ("name", fn.id)
        elif isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name):
            desc = ("self", fn.attr) if fn.value.id in ("self", "cls") \
                else ("attr", fn.value.id, fn.attr)
        else:
            return None
        ck = proj.resolve(ff, desc)
        if ck is None or ck not in proj.functions:
            return None
        rel = proj.find_release(ck, proto.name)
        if rel is None:
            return (f"callee {proj.pretty(ck)} performs no "
                    f"{proto.name} release on any reachable chain")
        chain, line = rel
        tgt = proj.functions[chain[-1]]
        return (f"on success ownership lands in "
                f"{proj.chain_str(chain)} (releases at "
                f"{tgt.relpath}:{line})")


class ThreadLifecycleRule(_FlowRule):
    name = "thread-lifecycle"
    description = ("a started thread nobody ever joins, stops, or "
                   "atexit-registers — the teardown-race class: it "
                   "outlives its owner and races interpreter/jax "
                   "client shutdown")

    def check_func(self, func: ast.AST, ctx: FileContext) -> None:
        if not self._scan(func, ctx).thread:
            return
        cfg = self._cfg(func, ctx)
        symbol = self._symbol(func, ctx)
        locals_bound: Dict[str, int] = {}
        for bid, idx, ev in cfg.events():
            if ev.kind == "assign" and isinstance(ev.node, ast.Assign) \
                    and _p.is_thread_ctor(ev.node.value):
                for tgt in ev.node.targets:
                    if isinstance(tgt, ast.Name):
                        locals_bound[tgt.id] = ev.line
            if ev.kind != "call" or not _p.thread_start(ev.node):
                continue
            recv, _meth = _p.call_desc(ev.node)
            if recv.endswith(("Thread()", "Worker()")):
                # inline Thread(...).start(): unjoinable from birth
                ctx.report(self, ev.line,
                           "fire-and-forget thread: "
                           f"{recv[:-2]}(...).start() can never be "
                           "joined, stopped, or atexit-registered",
                           symbol=symbol,
                           reason=("bind the thread and register its "
                                   "join, or hand it to an owner that "
                                   "outlives it",),
                           hops=(f"{ctx.relpath}:{ev.line}",))
                continue
            if recv not in locals_bound:
                continue              # self._t.start(): class-level check
            if self._owned_elsewhere(func, recv):
                continue              # lexically retired or handed off
            self._search_local(cfg, (bid, idx), ev, recv,
                               locals_bound[recv], ctx, symbol)

    @staticmethod
    def _owned_elsewhere(func: ast.AST, name: str) -> bool:
        """Lexical ownership scan: is ``name`` retired, stored onto an
        owner, passed to a call, or returned ANYWHERE in the function?

        Order-insensitive on purpose — ``self._t = t`` before
        ``t.start()`` is just as much a hand-off as after it, and a
        conditional ``if wait: t.join()`` is a deliberate policy, not
        a leak.  The path search only runs for names with no lexical
        out-edge at all, where a leak is unambiguous."""
        for n in ast.walk(func):
            if isinstance(n, ast.Call):
                if _p.thread_retire(n) == name:
                    return True
                argv = list(n.args) + [kw.value for kw in n.keywords]
                if any(name in _names(a) for a in argv):
                    return True
            elif isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                if name in _names(getattr(n, "value", None)):
                    tgts = n.targets if isinstance(n, ast.Assign) \
                        else [n.target]
                    if any(not isinstance(t, ast.Name) for t in tgts):
                        return True
            elif isinstance(n, (ast.Return, ast.Yield)):
                if name in _names(getattr(n, "value", None)):
                    return True
        return False

    def _search_local(self, cfg: CFG, start_pt, ev, name: str,
                      ctor_line: int, ctx: FileContext,
                      symbol: str) -> None:
        def on_event(e) -> Optional[str]:
            n, k = e.node, e.kind
            if k == "call":
                if _p.thread_retire(n) == name:
                    return "close"
                argv = list(n.args) + [kw.value for kw in n.keywords]
                if any(name in _names(a) for a in argv):
                    _recv, meth = _p.call_desc(n)
                    if meth in _INFALLIBLE_TRANSFER:
                        return "close"
                    return "transfer-after-raise"
            elif k == "assign":
                if name in _names(getattr(n, "value", None)):
                    tgts = n.targets if isinstance(n, ast.Assign) \
                        else [n.target]
                    if any(not isinstance(t, ast.Name) for t in tgts):
                        return "close"     # stored onto an owner
            elif k in ("return", "yield"):
                if name in _names(getattr(n, "value", None)):
                    return "close"
            return None

        path = leak_path(cfg, start_pt, on_event)
        if path is None:
            return
        ctx.report(self, ev.line,
                   f"thread '{name}' started here can leave the "
                   "function un-joined, un-stopped, and not "
                   "atexit-registered",
                   symbol=symbol,
                   reason=(f"constructed at {ctx.relpath}:{ctor_line}",
                           "join it (daemon or not), stop() it, or "
                           "register the join via atexit before losing "
                           "the last reference"),
                   hops=self._hops(cfg, path, ctx.relpath,
                                   lead_line=ev.line))

    def project_check(self, project) -> List:
        """Class-level half: ``self._t = Thread(...)`` + ``self._t
        .start()`` with no retire of ``_t`` anywhere in the module."""
        out: List[Finding] = []
        for rp, mod in sorted(project.modules.items()):
            retired: Set[str] = set()
            readers: Dict[str, Set[str]] = {}
            for ff in project.functions.values():
                if ff.relpath != rp:
                    continue
                for op, recv, _ln in ff.thread_ops:
                    if op == "retire":
                        retired.add(recv.rsplit(".", 1)[-1])
                for attr in ff.self_reads:
                    readers.setdefault(attr, set()).add(ff.qualname)
            for cls in mod.classes.values():
                ctors: Dict[str, int] = {}
                starts: Dict[str, Tuple[str, int]] = {}
                for meth_key in cls.methods.values():
                    ff = project.functions.get(meth_key)
                    if ff is None:
                        continue
                    for op, recv, ln in ff.thread_ops:
                        if op == "ctor-self":
                            ctors.setdefault(recv, ln)
                        elif op == "start" and recv.startswith("self."):
                            starts.setdefault(recv[5:],
                                              (ff.qualname, ln))
                for attr, (qual, line) in sorted(starts.items()):
                    if attr not in ctors or attr in retired:
                        continue
                    # a join through a local alias (``t, self._t =
                    # self._t, None; t.join()``) never produces a
                    # "retire" verb on the attribute — but it DOES
                    # read it.  Any reader other than the starter is
                    # taken as evidence of managed teardown.
                    if readers.get(attr, set()) - {qual}:
                        continue
                    out.append(Finding(
                        self.name, rp, line,
                        f"thread self.{attr} is started but never "
                        "joined/stopped/atexit-registered anywhere in "
                        "this module",
                        symbol=qual,
                        reason=(f"constructed at {rp}:{ctors[attr]}",
                                "give the owner a stop()/close() that "
                                "joins it, or register the join via "
                                "atexit"),
                        hops=(f"{rp}:{ctors[attr]}", f"{rp}:{line}")))
        return out


class BlockingUnderLockRule(_FlowRule):
    name = "blocking-under-lock"
    description = ("a call that can block indefinitely (queue get/put "
                   "without timeout, Thread.join(), socket recv, bare "
                   "wait()) is reachable while a lock is held — every "
                   "other acquirer of that lock stalls behind it")

    #: states explored per block before the dataflow gives up (a bound,
    #: not a correctness knob: lock nesting in this repo is depth ≤ 2)
    MAX_STATES = 8

    def check_func(self, func: ast.AST, ctx: FileContext) -> None:
        if not self._scan(func, ctx).locks:
            return
        cfg = self._cfg(func, ctx)
        symbol = self._symbol(func, ctx)
        reported: Set[int] = set()
        # state: frozenset of (lock display name, acquire line)
        seen: Dict[int, Set[frozenset]] = {}
        work: List[Tuple[int, frozenset]] = [(cfg.entry, frozenset())]
        while work:
            bid, state = work.pop()
            if state in seen.setdefault(bid, set()):
                continue
            if len(seen[bid]) >= self.MAX_STATES:
                continue
            seen[bid].add(state)
            blk = cfg.block(bid)
            for ev in blk.events:
                if ev.kind in _MAY_RAISE and blk.exc is not None:
                    # the handler sees exactly the locks held when the
                    # event raised, not the block's entry or exit set
                    work.append((blk.exc, state))
                state = self._apply(ev, state, ctx, symbol, reported,
                                    cfg, bid)
            for succ in blk.succs:
                work.append((succ, state))

    def _apply(self, ev, state: frozenset, ctx, symbol,
               reported: Set[int], cfg, bid: int) -> frozenset:
        n, k = ev.node, ev.kind
        if k == "with-enter":
            tok = _lock_token(n.context_expr)
            if tok is not None:
                return state | {(self._disp(n.context_expr), ev.line)}
        elif k == "with-exit":
            tok = _lock_token(n.context_expr)
            if tok is not None:
                disp = self._disp(n.context_expr)
                return frozenset(s for s in state if s[0] != disp)
        elif k == "call":
            recv, meth = _p.call_desc(n)
            if meth == "acquire" and recv and \
                    _lock_token(n.func.value) is not None:
                return state | {(recv, ev.line)}
            if meth == "release" and recv and \
                    _lock_token(n.func.value) is not None:
                return frozenset(s for s in state if s[0] != recv)
            if state:
                desc = _p.blocking_call(n)
                if desc is not None and ev.line not in reported:
                    reported.add(ev.line)
                    locks = ", ".join(sorted(s[0] for s in state))
                    acq = min(s[1] for s in state)
                    ctx.report(
                        self, ev.line,
                        f"{desc} while holding {locks}: every other "
                        "acquirer stalls until this unblocks",
                        symbol=symbol,
                        reason=(f"lock held since {ctx.relpath}:{acq}",
                                "use a timeout/_nowait variant, or move "
                                "the blocking call outside the held "
                                "region"),
                        hops=(f"{ctx.relpath}:{acq}",
                              f"{ctx.relpath}:{ev.line}"))
        return state

    @staticmethod
    def _disp(expr: ast.expr) -> str:
        return _p._expr_text(expr) or "<lock>"

    def project_check(self, project) -> List:
        """Interprocedural half: a call made while a lock is held whose
        callee (transitively) contains an indefinitely-blocking call."""
        out: List[Finding] = []
        for key in sorted(project.functions):
            ff = project.functions[key]
            seen_locks: Set[Tuple] = set()
            for cs in ff.calls:
                if not cs.held:
                    continue
                ck = project.resolve(ff, cs.desc)
                if ck is None or ck not in project.functions:
                    continue
                hit = project.find_blocking(ck)
                if hit is None:
                    continue
                dedup = (cs.held, hit[1])
                if dedup in seen_locks:
                    continue
                seen_locks.add(dedup)
                chain, (desc, bline) = hit
                tgt = project.functions[chain[-1]]
                locks = ", ".join(t[-1] for t in cs.held)
                out.append(Finding(
                    self.name, ff.relpath, cs.line,
                    f"call under lock {locks} reaches {desc} in "
                    f"{project.pretty(chain[-1])}",
                    symbol=ff.qualname,
                    reason=(f"held at the call site: {locks}",
                            "call chain: " + project.chain_str(
                                (key,) + chain),
                            f"blocks at {tgt.relpath}:{bline}"),
                    hops=(f"{ff.relpath}:{cs.line}",
                          f"{tgt.relpath}:{bline}")))
        return out
