"""mxlint pass 2.5: per-function control-flow graphs.

The flow-SENSITIVE tier (PR 20).  Passes 1-2 know *what* a function
does (facts, lexical context); this module knows *in which order and on
which paths* — the difference between "there is a ``release()`` in this
function" and "every path from the ``reserve()`` to every exit crosses
a ``release()``".  The costliest review fixes of PRs 11-19 were all
exit-path bugs (KV blocks leaked on a failed batch, spans unfinished
when a dispatch raised, membership daemons never joined): lexically the
cleanup existed; a path skipped it.

Design, in the order the constraints forced it:

- **Statement-granular basic blocks.**  Each :class:`Block` holds an
  ordered list of :class:`Event` records (calls in evaluation order,
  assignments, returns, raises, with-enter/with-exit).  Analyses walk
  *program points* ``(block, event_index)``, so an exception edge taken
  mid-block sees exactly the events that already executed.
- **One exception target per block** (``Block.exc``): blocks are split
  at ``try`` boundaries, so every event in a block shares the same
  innermost handler.  Only ``call``/``raise``/``assert``/``with-enter``
  events take the edge (:data:`MAY_RAISE`) — inventing a raise at
  ``x = 1`` would drown the real exit-path findings.
- **``finally`` (and ``with``) by duplication.**  A ``finally`` body is
  lowered once per way out — fall-through, each ``return``/``break``/
  ``continue``, and the exception path — the same strategy CPython's
  compiler used pre-3.8.  Duplication keeps every path explicit, which
  is the whole point of the tier; lint-scale functions keep it cheap.
- **Handler dispatch is conservative both ways**: an exception edge
  lands on a dispatch block fanning out to every handler, and falls
  through to the outer handler ONLY when no handler is a catch-all
  (bare / ``Exception`` / ``BaseException``) — otherwise the standard
  ``except Exception: cleanup(); raise`` idiom would leak through a
  phantom unmatched path.
- **Branch-arm facts** (``CFG.branches``): an ``if`` head records its
  test and which successor is the true/false arm, so the leak analysis
  can correlate ``tok = reserve()  # may be None`` with a later
  ``if tok is None: return`` instead of reporting the absent-resource
  arm as a leak.
- **Generators**: ``yield`` is an ordinary event, not an exit — an
  abandoned generator *can* strand a resource, but flagging every
  generator that holds anything across a yield would bury the signal.

Nested ``def``/``lambda`` bodies are *not* lowered into the enclosing
CFG (each def gets its own graph from the rule layer); their default
argument expressions, which do evaluate here, are.
"""
from __future__ import annotations

import ast
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from .core import FUNC_TYPES

__all__ = ["Event", "Block", "CFG", "build_cfg", "MAY_RAISE",
           "leak_path", "iter_walk"]

#: event kinds that grow an exception edge to the block's handler
MAY_RAISE = frozenset(("call", "raise", "assert", "with-enter"))

#: catch-all handler types: an exception edge into their dispatch block
#: cannot fall through to the outer handler
_CATCH_ALL = frozenset(("Exception", "BaseException"))


class Event:
    """One executed point inside a block.

    ``kind`` is one of ``call`` (an ``ast.Call``, emitted in evaluation
    order, inner calls first), ``assign`` (the store of an ``Assign``/
    ``AugAssign``/``AnnAssign``, emitted after its value's calls),
    ``return``/``raise``/``assert``/``yield``, and ``with-enter``/
    ``with-exit`` (``node`` is the ``ast.withitem``; the exit event is
    the ``__exit__`` guarantee, duplicated onto the exception path)."""

    __slots__ = ("kind", "node", "line")

    def __init__(self, kind: str, node: ast.AST, line: int):
        self.kind = kind
        self.node = node
        self.line = line

    def __repr__(self) -> str:
        return f"<Event {self.kind}@{self.line}>"


class Block:
    """Basic block: ordered events, normal successor edges, and the
    exception target every may-raise event in the block jumps to."""

    __slots__ = ("id", "events", "succs", "exc", "kind")

    def __init__(self, bid: int, exc: Optional[int], kind: str = "code"):
        self.id = bid
        self.events: List[Event] = []
        self.succs: List[int] = []
        self.exc = exc            # block id, or None for the two exits
        self.kind = kind          # "code" | "exit" | "raise"

    def __repr__(self) -> str:
        return (f"<Block {self.id} {self.kind} events={len(self.events)} "
                f"succs={self.succs} exc={self.exc}>")


class CFG:
    """One function's graph.  ``exit_id`` is the normal-return exit,
    ``raise_id`` the exceptional one; both are empty terminal blocks.
    ``branches`` maps an ``if``-head block id to ``(test_node,
    true_succ, false_succ)`` for guard-correlation in the analyses."""

    __slots__ = ("func", "blocks", "entry", "exit_id", "raise_id",
                 "branches")

    def __init__(self, func: ast.AST):
        self.func = func
        self.blocks: List[Block] = []
        self.branches: Dict[int, Tuple[ast.expr, int, int]] = {}
        self.exit_id = 0
        self.raise_id = 0
        self.entry = 0

    def block(self, bid: int) -> Block:
        return self.blocks[bid]

    def is_exit(self, bid: int) -> bool:
        return bid in (self.exit_id, self.raise_id)

    def events(self) -> List[Tuple[int, int, Event]]:
        """Every (block_id, index, event), block order — the scan the
        rules use to find acquire sites."""
        out = []
        for b in self.blocks:
            for i, e in enumerate(b.events):
                out.append((b.id, i, e))
        return out


class _Loop:
    __slots__ = ("continue_id", "break_id", "fin_depth")

    def __init__(self, continue_id: int, break_id: int, fin_depth: int):
        self.continue_id = continue_id
        self.break_id = break_id
        self.fin_depth = fin_depth


class _Lowerer:
    """One pass over one function body.  ``self.cur`` is the open block
    (None while the current point is unreachable, e.g. right after a
    ``return``); ``self.exc`` is the innermost handler target new
    blocks inherit."""

    def __init__(self, func: ast.AST):
        self.cfg = CFG(func)
        self.cfg.exit_id = self._new(exc=None, kind="exit").id
        self.cfg.raise_id = self._new(exc=None, kind="raise").id
        self.exc = self.cfg.raise_id
        # pending finally bodies, outermost first: (stmts-or-items,
        # kind "finally"|"with", exc target OUTSIDE the region)
        self.finallies: List[Tuple[object, str, int]] = []
        self.loops: List[_Loop] = []
        entry = self._new()
        self.cfg.entry = entry.id
        self.cur: Optional[Block] = entry

    # -- plumbing -----------------------------------------------------------
    def _new(self, exc: Optional[int] = -1, kind: str = "code") -> Block:
        b = Block(len(self.cfg.blocks),
                  self.exc if exc == -1 else exc, kind)
        self.cfg.blocks.append(b)
        return b

    def _edge(self, src: Block, dst: int) -> None:
        if dst not in src.succs:
            src.succs.append(dst)

    def _emit(self, kind: str, node: ast.AST) -> None:
        if self.cur is not None:
            line = getattr(node, "lineno", 0)
            if not line and isinstance(node, ast.withitem):
                # withitem carries no lineno of its own
                line = getattr(node.context_expr, "lineno", 0)
            self.cur.events.append(Event(kind, node, line))

    def _seal_to(self, dst: int) -> None:
        """Close the open block with an edge to ``dst``; current point
        becomes unreachable."""
        if self.cur is not None:
            self._edge(self.cur, dst)
            self.cur = None

    def _open(self, b: Block) -> None:
        self.cur = b

    # -- expression events --------------------------------------------------
    def _expr(self, node: Optional[ast.AST]) -> None:
        """Emit call/yield events of one expression in evaluation order
        (post-order: a call's argument calls precede it).  Nested
        def/lambda BODIES are skipped — they execute on some other
        frame's path — but their default-arg expressions run here."""
        if node is None or self.cur is None:
            return
        t = type(node)
        if t in FUNC_TYPES or t is ast.Lambda:
            for d in getattr(node, "decorator_list", ()):
                self._expr(d)
            for dflt in list(node.args.defaults) + \
                    [d for d in node.args.kw_defaults if d is not None]:
                self._expr(dflt)
            return
        if t is ast.Call:
            self._expr(node.func)
            for a in node.args:
                self._expr(a)
            for kw in node.keywords:
                self._expr(kw.value)
            self._emit("call", node)
            return
        if t in (ast.Yield, ast.YieldFrom, ast.Await):
            if getattr(node, "value", None) is not None:
                self._expr(node.value)
            self._emit("yield", node)
            return
        for child in ast.iter_child_nodes(node):
            self._expr(child)

    # -- statements ---------------------------------------------------------
    def lower(self, body: Sequence[ast.stmt]) -> CFG:
        self._stmts(body)
        self._seal_to(self.cfg.exit_id)      # fall off the end: return
        return self.cfg

    def _stmts(self, body: Sequence[ast.stmt]) -> None:
        for s in body:
            if self.cur is None:
                break                        # unreachable tail
            self._stmt(s)

    def _stmt(self, s: ast.stmt) -> None:   # noqa: C901 — one dispatch hub
        t = type(s)
        if t is ast.If:
            self._if(s)
        elif t in (ast.While, ast.For, ast.AsyncFor):
            self._loop_stmt(s)
        elif t in (ast.With, ast.AsyncWith):
            self._with(s)
        elif t is ast.Try:
            self._try(s)
        elif t is ast.Return:
            self._expr(s.value)
            self._emit("return", s)
            self._unwind(0)
            self._seal_to(self.cfg.exit_id)
        elif t is ast.Raise:
            self._expr(s.exc)
            self._expr(s.cause)
            self._emit("raise", s)
            if self.cur is not None:
                self.cur = None              # control goes via Block.exc
        elif t is ast.Break:
            if self.loops:
                lp = self.loops[-1]
                self._unwind(lp.fin_depth)
                self._seal_to(lp.break_id)
        elif t is ast.Continue:
            if self.loops:
                lp = self.loops[-1]
                self._unwind(lp.fin_depth)
                self._seal_to(lp.continue_id)
        elif t is ast.Assert:
            self._expr(s.test)
            self._expr(s.msg)
            self._emit("assert", s)
        elif t in (ast.Assign, ast.AugAssign, ast.AnnAssign):
            self._expr(getattr(s, "value", None))
            for tgt in (s.targets if t is ast.Assign else [s.target]):
                # subscript/attribute stores evaluate their base
                if not isinstance(tgt, ast.Name):
                    self._expr(tgt)
            if getattr(s, "value", None) is not None:
                self._emit("assign", s)
        elif t in FUNC_TYPES or t is ast.ClassDef:
            for d in s.decorator_list:
                self._expr(d)               # decorators run at def time
        else:
            # Expr, Delete, Import, Global, Pass, ...: events only
            for child in ast.iter_child_nodes(s):
                if isinstance(child, ast.expr):
                    self._expr(child)

    def _if(self, s: ast.If) -> None:
        self._expr(s.test)
        head = self.cur
        then_b = self._new()
        after = self._new()
        self._edge(head, then_b.id)
        if s.orelse:
            else_b = self._new()
            self._edge(head, else_b.id)
            self.cfg.branches[head.id] = (s.test, then_b.id, else_b.id)
            self._open(else_b)
            self._stmts(s.orelse)
            self._seal_to(after.id)
        else:
            self._edge(head, after.id)
            self.cfg.branches[head.id] = (s.test, then_b.id, after.id)
        self._open(then_b)
        self._stmts(s.body)
        self._seal_to(after.id)
        self._open(after)

    @staticmethod
    def _always_true(test: ast.expr) -> bool:
        return isinstance(test, ast.Constant) and bool(test.value)

    def _loop_stmt(self, s) -> None:
        is_while = isinstance(s, ast.While)
        if not is_while:
            self._expr(s.iter)              # iterable built once
        head = self._new()
        self._seal_to(head.id)
        self._open(head)
        if is_while:
            self._expr(s.test)
        body_b = self._new()
        after = self._new()
        self._edge(head, body_b.id)
        exits_normally = not (is_while and self._always_true(s.test))
        if exits_normally:
            if s.orelse:
                else_b = self._new()
                self._edge(head, else_b.id)
                self._open(else_b)
                self._stmts(s.orelse)
                self._seal_to(after.id)
            else:
                self._edge(head, after.id)
        self.loops.append(_Loop(head.id, after.id, len(self.finallies)))
        self._open(body_b)
        self._stmts(s.body)
        self._seal_to(head.id)              # back edge
        self.loops.pop()
        self._open(after)

    # -- finally / with duplication -----------------------------------------
    def _lower_cleanup(self, entry: Tuple[object, str, int]) -> None:
        """Inline ONE pending cleanup region (a ``finally`` body or a
        ``with`` exit) at the current point, with the exception target
        that surrounds that region."""
        stmts_or_items, kind, outer_exc = entry
        saved_exc, self.exc = self.exc, outer_exc
        if self.cur is not None and self.cur.events:
            nxt = self._new()
            self._seal_to(nxt.id)
            self._open(nxt)
        elif self.cur is not None:
            self.cur.exc = outer_exc
        if kind == "with":
            for item in reversed(stmts_or_items):
                self._emit("with-exit", item)
        else:
            self._stmts(stmts_or_items)
        self.exc = saved_exc

    def _unwind(self, down_to: int) -> None:
        """Run every pending cleanup from innermost down to (excluding)
        depth ``down_to`` — the ``return``/``break``/``continue`` path
        through the finallies."""
        for entry in reversed(self.finallies[down_to:]):
            if self.cur is None:
                return
            self._lower_cleanup(entry)

    def _exc_cleanup_copy(self, entry: Tuple[object, str, int]) -> int:
        """The exception-path copy of one cleanup region: a fresh block
        chain running the cleanup, then re-raising to the region's outer
        exception target.  Returns its entry block id."""
        _stmts, _kind, outer_exc = entry
        saved_cur = self.cur
        b = self._new(exc=outer_exc)
        self._open(b)
        self._lower_cleanup(entry)
        self._seal_to(outer_exc)
        self.cur = saved_cur
        return b.id

    def _with(self, s) -> None:
        for item in s.items:
            self._expr(item.context_expr)
            self._emit("with-enter", item)
        entry = (list(s.items), "with", self.exc)
        exc_copy = self._exc_cleanup_copy(entry)
        saved_exc, self.exc = self.exc, exc_copy
        body_b = self._new()
        self._seal_to(body_b.id)
        self._open(body_b)
        self.finallies.append(entry)
        self._stmts(s.body)
        self.finallies.pop()
        self.exc = saved_exc
        if self.cur is not None:
            self._lower_cleanup(entry)      # normal-exit copy

    def _try(self, s: ast.Try) -> None:
        outer_exc = self.exc
        if s.finalbody:
            entry = (list(s.finalbody), "finally", outer_exc)
            fin_exc = self._exc_cleanup_copy(entry)
            self.finallies.append(entry)
        else:
            entry = None
            fin_exc = outer_exc
        after = self._new(exc=outer_exc)

        if s.handlers:
            dispatch = self._new(exc=fin_exc, kind="code")
            body_exc = dispatch.id
        else:
            dispatch = None
            body_exc = fin_exc

        # try body
        body_b = self._new(exc=body_exc)
        self._seal_to(body_b.id)
        saved_exc, self.exc = self.exc, body_exc
        self._open(body_b)
        self._stmts(s.body)
        self.exc = saved_exc
        # orelse runs on normal body completion, OUTSIDE the handlers
        if s.orelse and self.cur is not None:
            ob = self._new(exc=fin_exc)
            self._seal_to(ob.id)
            self.exc, saved2 = fin_exc, self.exc
            self._open(ob)
            self._stmts(s.orelse)
            self.exc = saved2
        self._seal_to(after.id)

        # handlers fan out of the dispatch block
        if dispatch is not None:
            caught_all = False
            for h in s.handlers:
                if h.type is None:
                    caught_all = True
                else:
                    names = [n.id if isinstance(n, ast.Name) else
                             getattr(n, "attr", None)
                             for n in (h.type.elts if isinstance(
                                 h.type, ast.Tuple) else [h.type])]
                    if any(n in _CATCH_ALL for n in names):
                        caught_all = True
                hb = self._new(exc=fin_exc)
                self._edge(dispatch, hb.id)
                self.exc, saved3 = fin_exc, self.exc
                self._open(hb)
                self._stmts(h.body)
                self.exc = saved3
                self._seal_to(after.id)
            if not caught_all:
                # the exception may match no handler and keep unwinding
                self._edge(dispatch, fin_exc)

        if s.finalbody:
            self.finallies.pop()
            self._open(after)
            self._lower_cleanup(entry)      # normal-exit finally copy
        else:
            self._open(after)


def build_cfg(func: ast.AST) -> CFG:
    """Lower one ``FunctionDef``/``AsyncFunctionDef`` body to its CFG.
    Decorators and argument defaults execute at DEF time on the
    enclosing frame, so they are not part of this graph."""
    return _Lowerer(func).lower(func.body)


# -- generic path analyses ---------------------------------------------------

def iter_walk(cfg: CFG, start: Tuple[int, int],
              on_event: Callable[[Event], Optional[str]],
              branch_hint: Optional[Callable[[ast.expr, bool],
                                             Optional[str]]] = None,
              ) -> Optional[List[Tuple[int, int]]]:
    """DFS over program points from ``start`` (exclusive) hunting a path
    to a function exit that ``on_event`` never closes.

    ``on_event(event)`` returns ``"close"`` (this path is satisfied —
    stop exploring it), ``"transfer-after-raise"`` (the event closes the
    path ONLY if it completes: its exception edge is explored first with
    the path still open — the call-that-raised-took-no-ownership
    semantics), ``"noraise"`` (treat this event as unable to raise:
    skip its exception edge — for infallible builtins and methods of
    the managed resource itself), or None (keep walking).
    ``branch_hint(test, is_true_arm) -> "close" | None`` prunes
    guard-correlated arms (``if tok is None:`` — the arm where the
    resource provably doesn't exist).

    Returns the offending path as program points (including the exit
    block) or None if every path closes.  Cycle-safe: each point is
    expanded once; may-raise events additionally expand their block's
    exception target."""
    parent: Dict[Tuple[int, int], Tuple[int, int]] = {}
    seen: Set[Tuple[int, int]] = {start}
    stack: List[Tuple[int, int]] = [start]

    def _path(pt: Tuple[int, int]) -> List[Tuple[int, int]]:
        out = [pt]
        while pt in parent:
            pt = parent[pt]
            out.append(pt)
        out.reverse()
        return out

    def _push(src: Tuple[int, int], dst: Tuple[int, int]) -> None:
        if dst not in seen:
            seen.add(dst)
            parent[dst] = src
            stack.append(dst)

    while stack:
        bid, idx = stack.pop()
        blk = cfg.block(bid)
        if cfg.is_exit(bid):
            return _path((bid, idx))
        pt = (bid, idx)
        if idx < len(blk.events):
            ev = blk.events[idx]
            verdict = on_event(ev)
            if verdict == "close":
                # path satisfied; a release that itself raises is the
                # cleanup's bug, not this acquire's — no exc edge
                continue
            if verdict != "noraise" and ev.kind in MAY_RAISE and \
                    blk.exc is not None:
                _push(pt, (blk.exc, 0))
            if verdict == "transfer-after-raise":
                continue        # call completed => ownership moved on
            _push(pt, (bid, idx + 1))
            continue
        # end of block: follow normal successors (branch-aware)
        br = cfg.branches.get(bid)
        for succ in blk.succs:
            if br is not None and branch_hint is not None:
                test, true_id, false_id = br
                if succ in (true_id, false_id):
                    if branch_hint(test, succ == true_id) == "close":
                        continue
            _push(pt, (succ, 0))
    return None


def leak_path(cfg: CFG, acquire_pt: Tuple[int, int],
              on_event: Callable[[Event], Optional[str]],
              branch_hint=None) -> Optional[List[Tuple[int, int]]]:
    """Path from just AFTER the acquire event to an exit with no close:
    the resource-leak primitive.  ``acquire_pt`` is the acquire event's
    (block, index)."""
    bid, idx = acquire_pt
    return iter_walk(cfg, (bid, idx + 1), on_event,
                     branch_hint=branch_hint)
