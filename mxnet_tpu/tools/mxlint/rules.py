"""The repo's lint rules: four ported gates, three concurrency/config
contracts, and the three flow-sensitive rules from :mod:`.flow`.

Every rule encodes an invariant this codebase actually relies on — see
each rule's docstring for the failure mode it prevents.  All rules run
in the ONE walk :func:`mxlint.core.run_rules` makes per file.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import FUNC_TYPES, FileContext, Rule
from .flow import (BlockingUnderLockRule, ResourceLeakRule,
                   ThreadLifecycleRule)

__all__ = ["ALL_RULES", "make_rules", "declared_knobs", "BASE_RELPATH"]

BASE_RELPATH = "mxnet_tpu/base.py"


def _call_name(node: ast.expr) -> Optional[str]:
    """Trailing identifier of a call target: ``f(...)`` → ``f``,
    ``m.f(...)`` → ``f``."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


# -- ported gate 1: bare except ---------------------------------------------

class BareExceptRule(Rule):
    """``except:`` swallows SystemExit/KeyboardInterrupt and hides real
    faults — exactly what a resilience layer must never do.  Catch
    ``Exception`` (or narrower) and say why."""

    name = "bare-except"
    description = "no bare 'except:' clauses"
    interests = (ast.ExceptHandler,)

    def visit(self, node, ctx):
        if node.type is None:
            ctx.report(self, node.lineno,
                       "bare 'except:' swallows SystemExit/"
                       "KeyboardInterrupt and hides real faults; catch "
                       "Exception (or narrower)")


# -- ported gate 2: unbounded lru_cache on methods --------------------------

def _is_unbounded_lru(deco: ast.expr) -> bool:
    """``@lru_cache(maxsize=None)`` (bare ``@lru_cache`` or an int
    maxsize is bounded: fine)."""
    if not isinstance(deco, ast.Call):
        return False
    if _call_name(deco.func) != "lru_cache":
        return False
    return any(kw.arg == "maxsize" and isinstance(kw.value, ast.Constant)
               and kw.value.value is None for kw in deco.keywords)


class UnboundedLruRule(Rule):
    """``lru_cache(maxsize=None)`` on a METHOD keys every entry on
    ``self``: it pins each instance (and everything its entries close
    over — compiled XLA executables, in the Operator case this gate was
    written for) for the life of the process.  Module-level functions on
    immortal singletons are exempt; per-instance caches must be bounded
    (see ndarray.register._BoundedCache)."""

    name = "unbounded-lru-method"
    description = "no lru_cache(maxsize=None) on methods"
    interests = (ast.ClassDef,)

    def visit(self, node, ctx):
        # direct body items of ANY class — including classes defined
        # inside functions (factory-built classes leak the same way)
        for item in node.body:
            if not isinstance(item, FUNC_TYPES):
                continue
            for deco in item.decorator_list:
                if _is_unbounded_lru(deco):
                    ctx.report(
                        self, item.lineno,
                        f"unbounded lru_cache on method "
                        f"{node.name}.{item.name} pins instances (and "
                        f"their compiled executables) forever; use a "
                        f"bounded per-instance cache")


# -- ported gate 3: ad-hoc counter dicts ------------------------------------

_COUNTERISH_NAME = re.compile(r"(counters?|stats|metrics)$")


def _is_int_const(node) -> bool:
    return isinstance(node, ast.Constant) and type(node.value) is int


def _is_counter_dict_value(node) -> bool:
    """A NON-EMPTY dict literal with string keys and int-constant values
    (``{"steps_skipped": 0, ...}`` — the ad-hoc counter-surface shape PR 1
    and PR 2 each grew), or ``defaultdict(int)`` /
    ``collections.Counter()``.  Empty dicts stay legal: name-dedup
    counters (gluon.block, symbol) are keyed maps, not metric surfaces."""
    if isinstance(node, ast.Dict):
        return bool(node.values) and \
            all(isinstance(k, ast.Constant) and type(k.value) is str
                for k in node.keys) and \
            all(_is_int_const(v) for v in node.values)
    if isinstance(node, ast.Call):
        name = _call_name(node.func)
        if name == "defaultdict" and node.args and \
                isinstance(node.args[0], ast.Name) and \
                node.args[0].id == "int":
            return True
        if name == "Counter" and not node.args and not node.keywords:
            return True
    return False


class CounterDictRule(Rule):
    """Metrics go through ``observability.registry()`` — a third ad-hoc
    counter surface (module-level ``X_counters = {...: 0}`` dicts, the
    shape PR 1 and PR 2 each grew) must not come back.  Gate:
    module-level (or class-body-level) assignments of int-valued dict
    literals / ``defaultdict(int)`` to counter-ish names."""

    name = "counter-dict"
    description = "no ad-hoc module/class-level counter dicts"
    interests = (ast.Assign, ast.AnnAssign)
    # the registry IS the one sanctioned counter surface
    skip_paths = ("mxnet_tpu/observability/registry.py",)

    def visit(self, node, ctx):
        if ctx.func_stack:
            return                    # function-local dicts are fine
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif node.value is not None:  # AnnAssign
            targets, value = [node.target], node.value
        else:
            return
        names = [t.id.lower() for t in targets if isinstance(t, ast.Name)]
        if not any(_COUNTERISH_NAME.search(n) for n in names):
            return
        if _is_counter_dict_value(value):
            ctx.report(self, node.lineno,
                       "ad-hoc counter dict: use observability."
                       "registry() instead of growing another "
                       "disconnected metrics surface")


# -- ported gate 4: ad-hoc timing pairs -------------------------------------

def _is_clock_call(node) -> bool:
    """``time.time()`` / ``time.perf_counter()`` (incl. aliased imports
    like ``from time import perf_counter as _perf_counter``)."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr in ("time", "perf_counter") and \
            isinstance(fn.value, ast.Name) and fn.value.id == "time"
    if isinstance(fn, ast.Name):
        return "perf_counter" in fn.id
    return False


def _target_key(node):
    """Comparable key for ``t0 = ...`` / ``self._t0 = ...`` targets."""
    if isinstance(node, ast.Name):
        return ("n", node.id)
    if isinstance(node, ast.Attribute):
        return ("a", node.attr)
    return None


class TimingPairRule(Rule):
    """New wall-clock start/stop measurement outside the observability
    layer must go through ``trace.span`` — it lands in a histogram, the
    snapshot, the exporters, AND the unified chrome-trace timeline.
    Gate: a ``t0 = time.time()/perf_counter()`` assignment whose target
    is later subtracted from another clock call.  Findings anchor at the
    assignment line (one pragma there covers every paired stop)."""

    name = "timing-pair"
    description = "no ad-hoc clock pairs outside the metrics layer"
    interests = (ast.Assign, ast.BinOp)
    # observability/ and profiler.py ARE the metrics layer — the clocks
    # have to live somewhere
    skip_paths = ("mxnet_tpu/observability/", "mxnet_tpu/profiler.py")

    def begin_file(self, ctx):
        self._started: Dict[tuple, int] = {}
        self._stops: List[Tuple[tuple, int]] = []

    def visit(self, node, ctx):
        if isinstance(node, ast.Assign):
            if _is_clock_call(node.value):
                for t in node.targets:
                    key = _target_key(t)
                    if key is not None:
                        self._started.setdefault(key, node.lineno)
            return
        # BinOp: clock() - t0
        if isinstance(node.op, ast.Sub) and _is_clock_call(node.left):
            key = _target_key(node.right)
            if key is not None:
                self._stops.append((key, node.lineno))

    def end_file(self, ctx):
        reported: Set[int] = set()
        for key, stop_line in self._stops:
            line = self._started.get(key)
            if line is not None and line not in reported:
                reported.add(line)
                ctx.report(self, line,
                           f"ad-hoc timing pair (stopped at line "
                           f"{stop_line}): use observability.trace.span "
                           f"— histogram + unified timeline for free")


# -- new rule 1: lock discipline --------------------------------------------

_LOCK_FACTORIES = ("Lock", "RLock")
# method calls that mutate their receiver — counted as writes
_MUTATORS = frozenset((
    "append", "extend", "insert", "pop", "popitem", "remove", "clear",
    "add", "discard", "update", "setdefault", "appendleft", "popleft",
    "sort", "reverse"))
_INIT_METHODS = ("__init__", "__new__")


def _is_lock_factory(node) -> bool:
    return isinstance(node, ast.Call) and \
        _call_name(node.func) in _LOCK_FACTORIES and not node.args


def _self_attr(node) -> Optional[str]:
    """``self.X`` / ``cls.X`` (or a subscript of one) → ``X``."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and \
            node.value.id in ("self", "cls"):
        return node.attr
    return None


class LockDisciplineRule(Rule):
    """Static race detector for the codebase's lock convention.

    For any class holding a ``threading.Lock``/``RLock`` attribute (or a
    module holding one at top level): an attribute/global that is
    accessed under ``with <the lock>:`` in one place and *written*
    outside it in another is a race waiting for a free-threaded build —
    or an initialization-order bug today.  Writes include mutating
    method calls (``.append``/``.pop``/...) and subscript stores.

    Not flagged (by design, to stay useful):

    - writes in ``__init__``/``__new__`` (no concurrency before the
      object escapes) and module top-level assignments (import lock);
    - attributes never touched under the lock (plain unshared state);
    - writes inside methods whose name ends in ``_locked`` — the
      documented callers-hold-the-lock convention.

    Intentionally unlocked writes get ``# mxlint: disable=lock-discipline``
    with a justification, not a baseline entry.
    """

    name = "lock-discipline"
    description = "attributes guarded by a lock must be written under it"
    interests = (ast.Assign, ast.AugAssign, ast.AnnAssign, ast.Call,
                 ast.Attribute, ast.Name, ast.Global)

    def begin_file(self, ctx):
        # per-class: id(ClassDef) -> state
        self._classes: Dict[int, dict] = {}
        # module scope: locks, top-level global names, lock-guarded
        # evidence, and candidate write events (filtered at end_file
        # once the full top-level name set is known)
        self._mod_locks: Set[str] = set()
        self._mod_globals: Set[str] = set()
        self._mod_evidence: Set[str] = set()
        self._mod_writes: List[Tuple[str, int, bool]] = []
        self._fn_globals: Dict[int, Set[str]] = {}

    # -- helpers -----------------------------------------------------------
    def _cls(self, ctx) -> Optional[dict]:
        node = ctx.current_class()
        if node is None:
            return None
        st = self._classes.get(id(node))
        if st is None:
            st = self._classes[id(node)] = {
                "node": node, "locks": set(), "evidence": set(),
                "writes": []}
        return st

    def _guarded(self, ctx) -> bool:
        if ctx.holds_lock():
            return True
        fn = ctx.current_func()
        return fn is not None and fn.name.endswith("_locked")

    def _in_init(self, ctx) -> bool:
        fn = ctx.current_func()
        return fn is not None and fn.name in _INIT_METHODS

    def _declared_global(self, ctx, name: str) -> bool:
        fn = ctx.current_func()
        return fn is not None and \
            name in self._fn_globals.get(id(fn), ())

    def _class_write(self, ctx, attr: str, line: int) -> None:
        st = self._cls(ctx)
        if st is None:
            return
        guarded = self._guarded(ctx)
        if guarded:
            st["evidence"].add(attr)
        st["writes"].append((attr, line, guarded, self._in_init(ctx)))

    def _module_write(self, ctx, name: str, line: int) -> None:
        guarded = self._guarded(ctx)
        if guarded:
            self._mod_evidence.add(name)
        self._mod_writes.append((name, line, guarded))

    # -- walk --------------------------------------------------------------
    def visit(self, node, ctx):
        t = type(node)
        if t is ast.Global:
            fn = ctx.current_func()
            if fn is not None:
                self._fn_globals.setdefault(id(fn), set()).update(
                    node.names)
            return
        if t in (ast.Assign, ast.AugAssign, ast.AnnAssign):
            if t is ast.AnnAssign and node.value is None:
                return                # bare annotation: not a store
            targets = node.targets if t is ast.Assign else [node.target]
            value = node.value
            for tgt in targets:
                attr = _self_attr(tgt)
                if attr is not None and ctx.class_stack:
                    if _is_lock_factory(value) and \
                            not isinstance(tgt, ast.Subscript):
                        st = self._cls(ctx)
                        st["locks"].add(attr)
                    elif ctx.func_stack:
                        self._class_write(ctx, attr, tgt.lineno)
                    continue
                if isinstance(tgt, ast.Name):
                    if ctx.at_body_level() and ctx.class_stack and \
                            _is_lock_factory(value):
                        self._cls(ctx)["locks"].add(tgt.id)
                    elif not ctx.class_stack and ctx.at_body_level():
                        # module top level
                        if _is_lock_factory(value):
                            self._mod_locks.add(tgt.id)
                        else:
                            self._mod_globals.add(tgt.id)
                    elif ctx.func_stack and not ctx.class_stack and \
                            self._declared_global(ctx, tgt.id):
                        self._module_write(ctx, tgt.id, tgt.lineno)
                elif isinstance(tgt, ast.Subscript) and \
                        isinstance(tgt.value, ast.Name) and \
                        ctx.func_stack and not ctx.class_stack:
                    # X[...] = v on a module global needs no `global`
                    self._module_write(ctx, tgt.value.id, tgt.lineno)
            return
        if t is ast.Call:
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr in _MUTATORS:
                attr = _self_attr(fn.value)
                if attr is not None and ctx.class_stack and \
                        ctx.func_stack:
                    self._class_write(ctx, attr, node.lineno)
                elif isinstance(fn.value, ast.Name) and ctx.func_stack \
                        and not ctx.class_stack:
                    self._module_write(ctx, fn.value.id, node.lineno)
            return
        # loads under a held lock are evidence the lock guards that name
        if not self._guarded(ctx):
            return
        if t is ast.Attribute:
            attr = _self_attr(node)
            if attr is not None and ctx.class_stack:
                st = self._cls(ctx)
                st["evidence"].add(attr)
        elif t is ast.Name and isinstance(node.ctx, ast.Load) and \
                not ctx.class_stack and ctx.func_stack:
            self._mod_evidence.add(node.id)

    def end_file(self, ctx):
        for st in self._classes.values():
            if not st["locks"]:
                continue
            lock = sorted(st["locks"])[0]
            cls = st["node"].name
            seen: Set[Tuple[str, int]] = set()
            for attr, line, guarded, in_init in st["writes"]:
                if guarded or in_init or attr in st["locks"]:
                    continue
                if attr not in st["evidence"]:
                    continue          # never lock-guarded: not its state
                if (attr, line) in seen:
                    continue
                seen.add((attr, line))
                ctx.report(
                    self, line,
                    f"'{cls}.{attr}' is written here without holding "
                    f"'{lock}', but is accessed under it elsewhere in "
                    f"the class — take the lock, rename the method "
                    f"'*_locked' if callers hold it, or pragma with a "
                    f"justification")
        if self._mod_locks:
            lock = sorted(self._mod_locks)[0]
            seen = set()
            for name, line, guarded in self._mod_writes:
                if guarded or name in self._mod_locks or \
                        name not in self._mod_globals:
                    continue
                if name not in self._mod_evidence:
                    continue
                if (name, line) in seen:
                    continue
                seen.add((name, line))
                ctx.report(
                    self, line,
                    f"module global '{name}' is written here without "
                    f"holding '{lock}', but is accessed under it "
                    f"elsewhere in this module — take the lock or "
                    f"pragma with a justification")

    # -- interprocedural phase: deadlock classes ----------------------------

    @staticmethod
    def _tok_str(tok) -> str:
        scope, owner, name = tok
        if scope == "cls":
            return f"{owner.split('::')[-1]}.{name}"
        if scope == "mod":
            return f"{owner}::{name}"
        return f"{owner}.{name}"

    def project_check(self, project):
        """Held-lock propagation over the call graph:

        - **re-acquire**: a call made while holding a non-reentrant
          ``threading.Lock`` that (transitively) acquires the SAME lock
          self-deadlocks on first contention-free run — ``Lock`` is not
          re-entrant.
        - **lock-order inversion**: lock A taken while holding B in one
          code path and B while holding A in another is the classic
          two-thread deadlock; every edge carries the call chain that
          produced it."""
        from .core import Finding
        out = []
        seen_reacq = set()
        # (A, B) -> (relpath, line, symbol, reason)
        edges: Dict[tuple, tuple] = {}
        for key in sorted(project.functions):
            ff = project.functions[key]
            sym = None if ff.qualname == "<module>" else ff.qualname
            # intra-function evidence (with-nesting and acquire() calls)
            for tok, line, held in ff.acquires:
                if tok[0] not in ("cls", "mod"):
                    continue
                for h in held:
                    if h == tok:
                        if project.lock_kinds.get(tok) == "Lock" and \
                                (ff.relpath, line) not in seen_reacq:
                            seen_reacq.add((ff.relpath, line))
                            out.append(Finding(
                                self.name, ff.relpath, line,
                                f"re-acquires non-reentrant "
                                f"'{self._tok_str(tok)}' already held in "
                                f"this function — threading.Lock "
                                f"self-deadlocks; use RLock or split a "
                                f"'*_locked' helper", symbol=sym))
                    elif (h, tok) not in edges:
                        edges[(h, tok)] = (
                            ff.relpath, line, sym,
                            (f"{project.pretty(key)} acquires "
                             f"'{self._tok_str(tok)}' while holding "
                             f"'{self._tok_str(h)}' "
                             f"({ff.relpath}:{line})",))
            # interprocedural: calls made with locks held
            for cs in ff.calls:
                if not cs.held:
                    continue
                ck = project.resolve(ff, cs.desc)
                if ck is None:
                    continue
                for tok, (chain, aline) in \
                        project.find_acquires(ck).items():
                    tail = project.functions[chain[-1]]
                    if tok in cs.held:
                        if project.lock_kinds.get(tok) == "Lock" and \
                                (ff.relpath, cs.line) not in seen_reacq:
                            seen_reacq.add((ff.relpath, cs.line))
                            out.append(Finding(
                                self.name, ff.relpath, cs.line,
                                f"this call re-acquires non-reentrant "
                                f"'{self._tok_str(tok)}' already held "
                                f"(via {project.chain_str(chain)}) — "
                                f"threading.Lock self-deadlocks; use "
                                f"RLock or call a '*_locked' variant",
                                symbol=sym,
                                reason=(f"{project.pretty(key)} holds "
                                        f"'{self._tok_str(tok)}' at the "
                                        f"call ({ff.relpath}:{cs.line})",
                                        f"call chain: "
                                        f"{project.chain_str(chain)}",
                                        f"{project.pretty(tail.key)} "
                                        f"acquires it again at "
                                        f"{tail.relpath}:{aline}")))
                        continue
                    for h in cs.held:
                        if h != tok and (h, tok) not in edges:
                            edges[(h, tok)] = (
                                ff.relpath, cs.line, sym,
                                (f"{project.pretty(key)} holds "
                                 f"'{self._tok_str(h)}' and calls "
                                 f"{project.chain_str(chain)}, which "
                                 f"acquires '{self._tok_str(tok)}' at "
                                 f"{tail.relpath}:{aline}",))
        reported = set()
        for a, b in sorted(edges):
            if (b, a) not in edges or (b, a) in reported:
                continue
            reported.add((a, b))
            rp, line, sym, why = edges[(a, b)]
            rp2, line2, _sym2, why2 = edges[(b, a)]
            out.append(Finding(
                self.name, rp, line,
                f"lock-order inversion: '{self._tok_str(a)}' is held "
                f"while taking '{self._tok_str(b)}' here, but "
                f"{rp2}:{line2} takes them in the OPPOSITE order — two "
                f"threads on these paths deadlock; pick one global "
                f"order", symbol=sym, reason=why + why2))
        return out


# -- new rule 2: collective safety (interprocedural) ------------------------

class CollectiveSafetyRule(Rule):
    """Collectives must be reached by EVERY host or by none: a call to
    ``allgather_*``/``allreduce_host``/``broadcast_host``/``barrier``
    reached from a branch conditioned on the process index (``rank``,
    ``process_index``, ``host_id``, ...) means some hosts enter the
    collective and the rest never will — the whole fleet then blocks
    until the DCN timeout.  This is the exact bug class the PR 4
    checkpoint-boundary metric gather was designed around.

    Interprocedural since PR 6: the collective no longer has to sit
    *lexically* under the branch — a helper called under ``if rank ==
    0:`` that (transitively, call-depth-bounded) reaches a collective is
    flagged at the call site, with the call chain in the finding's
    ``reason``.  Hoist the collective above the branch, or branch on
    fleet-uniform state only (``is_initialized()``, ``num_workers``)."""

    name = "collective-safety"
    description = "no collectives (even via helpers) under host-divergent " \
                  "branches"
    interests = ()

    def project_check(self, project):
        from .core import Finding
        out = []
        flagged = set()                       # (relpath, line) dedup
        for key in sorted(project.functions):
            ff = project.functions[key]
            sym = None if ff.qualname == "<module>" else ff.qualname
            # direct: the collective itself sits under the branch
            for name, line, tok in ff.collectives:
                if tok is None or (ff.relpath, line) in flagged:
                    continue
                flagged.add((ff.relpath, line))
                out.append(Finding(
                    self.name, ff.relpath, line,
                    f"collective '{name}()' under a branch conditioned "
                    f"on host-divergent '{tok}': hosts taking the other "
                    f"arm never reach it and the fleet deadlocks — "
                    f"hoist it out of the branch", symbol=sym))
            # transitive: a call under the branch reaches a collective
            for cs in ff.calls:
                if cs.host_tok is None or (ff.relpath, cs.line) in flagged:
                    continue
                ck = project.resolve(ff, cs.desc)
                if ck is None:
                    continue
                hit = project.find_collective(ck)
                if hit is None:
                    continue
                chain, (cname, cline) = hit
                tail = project.functions[chain[-1]]
                flagged.add((ff.relpath, cs.line))
                out.append(Finding(
                    self.name, ff.relpath, cs.line,
                    f"collective '{cname}()' is reached from this call "
                    f"under a branch conditioned on host-divergent "
                    f"'{cs.host_tok}' (via {project.chain_str(chain)}): "
                    f"hosts taking the other arm never enter it and the "
                    f"fleet deadlocks — hoist the call out of the branch "
                    f"or make the branch fleet-uniform",
                    symbol=sym,
                    reason=(f"{project.pretty(key)} calls "
                            f"{project.pretty(ck)} under a branch on "
                            f"'{cs.host_tok}' "
                            f"({ff.relpath}:{cs.line})",
                            f"call chain: {project.chain_str(chain)}",
                            f"{project.pretty(tail.key)} calls "
                            f"'{cname}()' at {tail.relpath}:{cline}")))
        return out


# -- new rule 4 (PR 6): hot-path purity -------------------------------------

class HotPathPurityRule(Rule):
    """The per-op dispatch path (engine push, bulk-segment defer/flush —
    functions marked ``@hot_path("dispatch")``) runs ~10^5 times per
    second; PR-2 bought its 4.2x by keeping it to plain int adds and
    dict hits.  Anything reachable from a dispatch root — helpers
    included, which is why this rule is interprocedural — must not
    allocate host arrays, read the environment, create locks, or log:
    each of those is 1-50µs on a ~6µs path, and env reads/logging also
    take process-wide locks.

    Deliberate cold paths reached from hot roots (one-time singleton
    init, per-signature compile misses) carry a pragma WITH a
    justification; the finding's ``reason`` shows the call chain so the
    reader can judge the claim."""

    name = "hot-path-purity"
    description = "no alloc/env-read/lock-creation/logging reachable " \
                  "from @hot_path('dispatch') roots"
    interests = ()
    #: sanctioned accessors: ``_raw_env`` IS the memoized env fast path,
    #: and ``get_env`` is the declared-knob reader — their internal
    #: environ reads are their job; a HOT caller of either is still
    #: flagged at its own call site (env-read event)
    _SANCTIONED = frozenset((("mxnet_tpu/engine.py", "_raw_env"),
                             ("mxnet_tpu/base.py", "get_env")))

    def project_check(self, project):
        from .core import Finding
        out = []
        roots = project.hot_roots(("dispatch",))
        reach = project.reachable(roots)
        for key in sorted(reach):
            ff = project.functions[key]
            if (ff.relpath, ff.qualname) in self._SANCTIONED:
                continue
            chain = reach[key]
            sym = None if ff.qualname == "<module>" else ff.qualname
            via = (f" via {project.chain_str(chain)}"
                   if len(chain) > 1 else "")
            for kind, line, what in ff.impure:
                out.append(Finding(
                    self.name, ff.relpath, line,
                    f"{kind} ({what}) on the dispatch hot path — "
                    f"reachable from @hot_path('dispatch') root "
                    f"{project.pretty(chain[0])}{via}; hoist it off the "
                    f"per-op path, or pragma with a justification if "
                    f"this is a deliberate cold branch",
                    symbol=sym,
                    reason=(f"dispatch root: {project.pretty(chain[0])}",
                            f"call chain: {project.chain_str(chain)}",
                            f"{kind}: {what} at {ff.relpath}:{line}")))
        return out


# -- new rule 5 (PR 6): hidden host sync ------------------------------------

class HiddenHostSyncRule(Rule):
    """``.asnumpy()`` / ``.item()`` on an NDArray is a device→host round
    trip: it blocks on the async engine, flushes any pending bulk
    segment, and serializes dispatch against compute — the exact stall
    PAPER.md's dependency engine exists to avoid.  Library code must
    treat them as *boundaries*, never plumbing.

    Two tiers:

    - every ``.asnumpy()``/``.item()`` call site in the package is
      flagged (deliberate export boundaries carry a justification
      pragma; pre-existing debt is baseline-frozen file-by-file);
    - inside code reachable from a ``@hot_path`` root (training step or
      dispatch), the finding escalates and additionally covers value
      casts of method-call results (``float(loss.sum())``) and numpy
      coercion (``np.asarray(x)``) — the disguised syncs a reviewer
      misses."""

    name = "hidden-host-sync"
    description = "no NDArray host syncs (.asnumpy/.item/casts) on or " \
                  "near hot paths"
    interests = ()

    def project_check(self, project):
        from .core import Finding
        out = []
        roots = project.hot_roots(("dispatch", "step"))
        reach = project.reachable(roots)
        for key in sorted(project.functions):
            ff = project.functions[key]
            sym = None if ff.qualname == "<module>" else ff.qualname
            chain = reach.get(key)
            for kind, line, what in ff.syncs:
                if chain is not None:
                    out.append(Finding(
                        self.name, ff.relpath, line,
                        f"host sync {what} on a hot path — reachable "
                        f"from @hot_path root "
                        f"{project.pretty(chain[0])}: every call is a "
                        f"device round-trip that serializes the async "
                        f"engine; keep the value on device, batch the "
                        f"transfer, or pragma with a justification",
                        symbol=sym,
                        reason=(f"hot root: {project.pretty(chain[0])}",
                                f"call chain: "
                                f"{project.chain_str(chain)}",
                                f"sync: {what} at {ff.relpath}:{line}")))
                elif kind in ("asnumpy", "item"):
                    out.append(Finding(
                        self.name, ff.relpath, line,
                        f"host sync {what}: device round-trip that "
                        f"serializes the async engine — if this is a "
                        f"deliberate data-export boundary, pragma it "
                        f"with a justification; it must not creep onto "
                        f"a hot path", symbol=sym))
        return out


# -- new rule 3: env-knob registry ------------------------------------------

_KNOB_PREFIXES = ("MXNET_", "MXTPU_")

_declared_cache: Optional[Set[str]] = None


def declared_knobs(repo_root: str, refresh: bool = False) -> Set[str]:
    """The knob table: every name registered via ``register_env(...)``
    in ``mxnet_tpu/base.py``, extracted statically (no package import —
    linting must not pay a jax import)."""
    global _declared_cache
    if _declared_cache is not None and not refresh:
        return _declared_cache
    names: Set[str] = set()
    path = os.path.join(repo_root, *BASE_RELPATH.split("/"))
    try:
        with open(path, "r", encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return names                  # no table: nothing is declared
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                _call_name(node.func) == "register_env" and node.args and \
                isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str):
            names.add(node.args[0].value)
    _declared_cache = names
    return names


class EnvKnobRule(Rule):
    """Every ``MXNET_*``/``MXTPU_*`` environment read goes through the
    declared knob table (``register_env``/``get_env`` in
    ``mxnet_tpu/base.py`` — name, typed default, description), from
    which the README knob reference is generated.  A raw
    ``os.environ.get("MXNET_X", ...)`` silently forks the default from
    the documented one; an undeclared name read via ``get_env`` is a
    knob the docs don't know exists.  Module-level ``X_ENV = "MXTPU_Y"``
    name constants are resolved.

    Writes are checked too (PR 8): ``os.environ["MXNET_X"] = v`` of a
    name the table doesn't declare is a knob being *invented* at the
    mutation site — the self-tuning controllers apply their decisions
    exactly this way, so an undeclared write is a controller steering a
    knob the docs, the typed-default parser, and the README table have
    never heard of.  Declared-name writes are the sanctioned apply
    path."""

    name = "env-knob"
    description = "MXNET_*/MXTPU_* reads go through base.get_env"
    interests = (ast.Assign, ast.Call, ast.Subscript)
    skip_paths = (BASE_RELPATH,)      # the table itself reads os.environ

    def __init__(self, repo_root: str):
        self._repo_root = repo_root

    def begin_file(self, ctx):
        self._consts: Dict[str, str] = {}
        # (kind, key_expr, lineno): resolved at end_file so constants
        # defined later in the module still resolve
        self._events: List[Tuple[str, ast.expr, int]] = []

    def _knob_name(self, expr: ast.expr) -> Optional[str]:
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            v = expr.value
        elif isinstance(expr, ast.Name):
            v = self._consts.get(expr.id)
        else:
            return None
        if v is not None and v.startswith(_KNOB_PREFIXES):
            return v
        return None

    def visit(self, node, ctx):
        t = type(node)
        if t is ast.Assign:
            if ctx.at_body_level() and not ctx.class_stack and \
                    isinstance(node.value, ast.Constant) and \
                    isinstance(node.value.value, str):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self._consts[tgt.id] = node.value.value
            return
        if t is ast.Subscript:
            base = node.value
            on_environ = (isinstance(base, ast.Attribute)
                          and base.attr == "environ") or \
                         (isinstance(base, ast.Name)
                          and base.id == "environ")
            if on_environ and isinstance(node.ctx, ast.Load):
                self._events.append(("read", node.slice, node.lineno))
            elif on_environ and isinstance(node.ctx, ast.Store):
                self._events.append(("write", node.slice, node.lineno))
            return
        # Call
        fn = node.func
        name = _call_name(fn)
        if name == "get" and isinstance(fn, ast.Attribute) and (
                (isinstance(fn.value, ast.Attribute)
                 and fn.value.attr == "environ")
                or (isinstance(fn.value, ast.Name)
                    and fn.value.id == "environ")):
            if node.args:
                self._events.append(("read", node.args[0], node.lineno))
        elif name == "getenv" and node.args:
            self._events.append(("read", node.args[0], node.lineno))
        elif name == "get_env" and node.args:
            self._events.append(("declared", node.args[0], node.lineno))
        elif name == "register_env":
            self._events.append(("register", fn, node.lineno))
        elif name == "_raw_env":
            for a in node.args:
                self._events.append(("declared", a, node.lineno))

    def end_file(self, ctx):
        declared = declared_knobs(self._repo_root)
        for kind, expr, line in self._events:
            if kind == "register":
                ctx.report(self, line,
                           f"register_env() outside {BASE_RELPATH}: "
                           f"knobs are declared in ONE table so the "
                           f"README reference can be generated from it")
                continue
            knob = self._knob_name(expr)
            if knob is None:
                continue
            if kind == "read":
                ctx.report(self, line,
                           f"direct environ read of '{knob}': route it "
                           f"through mxnet_tpu.base.get_env so the "
                           f"declared default/type applies (register_env"
                           f" in {BASE_RELPATH})")
            elif kind == "write" and knob not in declared:
                ctx.report(self, line,
                           f"environ write of undeclared knob '{knob}': "
                           f"mutating a knob outside the declared table "
                           f"invents config the docs/typed defaults "
                           f"never see — register_env('{knob}', ...) in "
                           f"{BASE_RELPATH} first")
            elif kind == "declared" and knob not in declared:
                ctx.report(self, line,
                           f"env knob '{knob}' is not declared: add "
                           f"register_env('{knob}', <default>, <type>, "
                           f"<help>) in {BASE_RELPATH}")


def make_rules(repo_root: str) -> List[Rule]:
    """Fresh rule instances for one lint run (rules carry per-file
    scratch state, so runs must not share them across threads)."""
    return [
        BareExceptRule(),
        UnboundedLruRule(),
        CounterDictRule(),
        TimingPairRule(),
        LockDisciplineRule(),
        CollectiveSafetyRule(),
        HotPathPurityRule(),
        HiddenHostSyncRule(),
        EnvKnobRule(repo_root),
        # the flow-sensitive tier (PR 20): CFG-based exit-path analyses
        ResourceLeakRule(),
        ThreadLifecycleRule(),
        BlockingUnderLockRule(),
    ]


ALL_RULES = tuple(r.name for r in make_rules("."))
