"""mxlint ``--fix``: mechanical, behavior-preserving rewrites.

Two fixers, both deliberately narrow — a fixer that guesses is worse
than a finding the author resolves by hand:

- **env-read**: a raw ``os.environ.get("MXNET_X", ...)`` /
  ``os.environ["MXNET_X"]`` / ``os.getenv("MXNET_X", ...)`` read of a
  knob that IS declared in the ``base.py`` table becomes
  ``get_env("MXNET_X")`` (the declared default/type applies — which is
  the point: a raw read silently forks the default from the documented
  one).  Undeclared names are left alone: rewriting them would change
  behavior without a table entry to define it.  The ``get_env`` import
  is added if the module doesn't already bind the name.
- **with-lock**: a same-block ``X.acquire()`` … ``X.release()``
  statement pair becomes ``with X:`` around the statements between
  them.  Only when the region is provably equivalent: no
  return/break/continue (the original pair leaks the lock on those
  paths — rewriting would CHANGE behavior, and the leak deserves a
  human look, which lock-discipline now gives it), and no other
  acquire/release of the same lock inside (the release/re-acquire
  dance in ``register.py::_try_defer`` must never be "simplified").

Both fixers are idempotent: running ``--fix`` on already-fixed source
is a no-op, and the CLI validates by re-linting the fixed tree.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import pragma_map

__all__ = ["fix_source", "Fix"]

_KNOB_PREFIXES = ("MXNET_", "MXTPU_")


def _pragma_opts_out(pragmas: Dict[int, Set[str]], lines: Sequence[str],
                     line: int, rule: str) -> bool:
    """A ``# mxlint: disable=<rule>`` pragma covering ``line`` opts the
    site out of fixing too — the author already declared the raw form
    intentional (same same-line / standalone-comment-above contract as
    finding suppression)."""
    names = pragmas.get(line)
    if names and ("all" in names or rule in names):
        return True
    prev = line - 1
    names = pragmas.get(prev)
    return bool(names and 1 <= prev <= len(lines)
                and lines[prev - 1].lstrip().startswith("#")
                and ("all" in names or rule in names))


class Fix:
    """One applied (or proposed) rewrite."""

    __slots__ = ("kind", "line", "detail")

    def __init__(self, kind: str, line: int, detail: str):
        self.kind = kind
        self.line = line
        self.detail = detail

    def __repr__(self) -> str:
        return f"[fix:{self.kind}] line {self.line}: {self.detail}"


# -- fixer 1: raw environ reads -> get_env ----------------------------------

def _environ_read_span(node: ast.AST) -> Optional[Tuple[str, ast.AST]]:
    """(knob name, call/subscript node) for a raw environ read of a
    string-literal MXNET_*/MXTPU_* name, else None."""
    if isinstance(node, ast.Subscript):
        base = node.value
        is_env = (isinstance(base, ast.Attribute) and base.attr == "environ") \
            or (isinstance(base, ast.Name) and base.id == "environ")
        if is_env and isinstance(node.ctx, ast.Load) and \
                isinstance(node.slice, ast.Constant) and \
                isinstance(node.slice.value, str):
            return node.slice.value, node
        return None
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    name = fn.attr if isinstance(fn, ast.Attribute) else \
        fn.id if isinstance(fn, ast.Name) else None
    if name == "get" and isinstance(fn, ast.Attribute):
        recv = fn.value
        is_env = (isinstance(recv, ast.Attribute) and recv.attr == "environ")\
            or (isinstance(recv, ast.Name) and recv.id == "environ")
        if not is_env:
            return None
    elif name != "getenv":
        return None
    if node.args and isinstance(node.args[0], ast.Constant) and \
            isinstance(node.args[0].value, str):
        return node.args[0].value, node
    return None


def _binds_get_env(tree: ast.AST) -> bool:
    for n in ast.walk(tree):
        if isinstance(n, ast.ImportFrom):
            if any(a.name == "get_env" and a.asname is None
                   for a in n.names):
                return True
        elif isinstance(n, ast.FunctionDef) and n.name == "get_env":
            return True
    return False


def _get_env_import_line(relpath: str) -> str:
    """Repo-idiomatic import for ``get_env`` given the module location."""
    parts = relpath.replace("\\", "/").split("/")
    if parts[0] == "mxnet_tpu" and len(parts) > 1:
        depth = len(parts) - 1          # mxnet_tpu/x.py -> 1 -> .base
        return f"from {'.' * depth}base import get_env"
    return "from mxnet_tpu.base import get_env"


def _fix_env_reads(source: str, relpath: str, declared: Set[str]
                   ) -> Tuple[str, List[Fix]]:
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return source, []
    pragmas = pragma_map(source)
    plain_lines = source.splitlines()
    targets = []                        # (lineno, col, end_col, knob)
    for node in ast.walk(tree):
        hit = _environ_read_span(node)
        if hit is None:
            continue
        knob, span = hit
        if not knob.startswith(_KNOB_PREFIXES) or knob not in declared:
            continue
        if span.lineno != span.end_lineno:
            continue                    # multi-line call: hand-fix
        if _pragma_opts_out(pragmas, plain_lines, span.lineno, "env-knob"):
            continue                    # author declared the raw read
        targets.append((span.lineno, span.col_offset, span.end_col_offset,
                        knob))
    # nested reads (a read as another read's default arg): keep only the
    # OUTERMOST span — rewriting it replaces the whole expression in one
    # shot, while rewriting the inner one first would shift the line and
    # leave the outer span pointing past the call (silent corruption)
    targets = [t for t in targets
               if not any(o is not t and o[0] == t[0]
                          and o[1] <= t[1] and t[2] <= o[2]
                          for o in targets)]
    if not targets:
        return source, []
    lines = source.splitlines(keepends=True)
    fixes: List[Fix] = []
    # bottom-up, right-to-left so earlier spans stay valid
    for lineno, col, end_col, knob in sorted(targets, reverse=True):
        line = lines[lineno - 1]
        lines[lineno - 1] = (line[:col] + f'get_env("{knob}")'
                            + line[end_col:])
        fixes.append(Fix("env-read", lineno,
                         f"raw environ read of {knob} -> get_env({knob!r})"))
    fixes.reverse()
    new_source = "".join(lines)
    if not _binds_get_env(tree):
        new_source = _insert_import(new_source,
                                    _get_env_import_line(relpath))
        fixes.append(Fix("env-read", 0, "added get_env import"))
    return new_source, fixes


def _insert_import(source: str, import_line: str) -> str:
    """Insert after the last top-level import (or the module docstring)."""
    tree = ast.parse(source)
    last = 0
    for node in tree.body:
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            last = node.end_lineno or node.lineno
        elif last == 0 and isinstance(node, ast.Expr) and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            last = node.end_lineno or node.lineno    # docstring
    lines = source.splitlines(keepends=True)
    lines.insert(last, import_line + "\n")
    return "".join(lines)


# -- fixer 2: same-block acquire()/release() pair -> with -------------------

def _lockish(expr: ast.expr) -> Optional[str]:
    """Source text of a lock-ish receiver (name contains 'lock')."""
    if isinstance(expr, ast.Attribute) and "lock" in expr.attr.lower():
        return ast.unparse(expr)
    if isinstance(expr, ast.Name) and "lock" in expr.id.lower():
        return expr.id
    return None


def _acq_rel_stmt(stmt: ast.stmt) -> Optional[Tuple[str, str]]:
    """("acquire"|"release", receiver source) for a bare
    ``X.acquire()``/``X.release()`` statement."""
    if not isinstance(stmt, ast.Expr) or \
            not isinstance(stmt.value, ast.Call) or stmt.value.args or \
            stmt.value.keywords:
        return None
    fn = stmt.value.func
    if not isinstance(fn, ast.Attribute) or \
            fn.attr not in ("acquire", "release"):
        return None
    recv = _lockish(fn.value)
    if recv is None:
        return None
    return fn.attr, recv


def _region_is_safe(stmts: Sequence[ast.stmt], recv: str) -> bool:
    """No early exits — return/break/continue/raise all leave the pair's
    region with the lock still HELD; rewriting to ``with`` would release
    it there, changing behavior — no other acquire/release of the SAME
    lock, and no multi-line string literals (the rewrite re-indents raw
    lines, which would change the string's VALUE)."""
    for stmt in stmts:
        for n in ast.walk(stmt):
            if isinstance(n, (ast.Return, ast.Break, ast.Continue,
                              ast.Raise)):
                return False
            if isinstance(n, (ast.Constant, ast.JoinedStr)) and \
                    getattr(n, "end_lineno", n.lineno) != n.lineno and \
                    (isinstance(n, ast.JoinedStr)
                     or isinstance(n.value, (str, bytes))):
                return False
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr in ("acquire", "release"):
                r = _lockish(n.func.value)
                if r == recv:
                    return False
    return True


def _find_pair(body: Sequence[ast.stmt], pragmas: Dict[int, Set[str]],
               lines: Sequence[str]) -> Optional[Tuple[int, int, str]]:
    """First same-block (acquire_idx, release_idx, receiver) pair whose
    region qualifies, else None."""
    for i, stmt in enumerate(body):
        ar = _acq_rel_stmt(stmt)
        if ar is None or ar[0] != "acquire":
            continue
        if _pragma_opts_out(pragmas, lines, stmt.lineno,
                            "lock-discipline"):
            continue                    # author declared the raw pair
        recv = ar[1]
        for j in range(i + 1, len(body)):
            ar2 = _acq_rel_stmt(body[j])
            if ar2 is not None and ar2[0] == "release" and ar2[1] == recv:
                if _region_is_safe(body[i + 1:j], recv):
                    return i, j, recv
                break                   # unsafe region: leave this pair
            # a nested acquire/release of the same lock anywhere between
            # disqualifies via _region_is_safe at match time
    return None


def _iter_bodies(tree: ast.AST):
    for node in ast.walk(tree):
        for field in ("body", "orelse", "finalbody"):
            body = getattr(node, field, None)
            if isinstance(body, list) and body and \
                    isinstance(body[0], ast.stmt):
                yield body


def _fix_one_pair(source: str) -> Tuple[str, Optional[Fix]]:
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return source, None
    pragmas = pragma_map(source)
    plain_lines = source.splitlines()
    for body in _iter_bodies(tree):
        pair = _find_pair(body, pragmas, plain_lines)
        if pair is None:
            continue
        i, j, recv = pair
        acq, rel = body[i], body[j]
        lines = source.splitlines(keepends=True)
        indent = lines[acq.lineno - 1][:acq.col_offset]
        # region lines: everything between the acquire and release stmts
        region_start = acq.end_lineno           # 0-based index of line after
        region_end = rel.lineno - 1             # 0-based index of release
        region = [("    " + ln if ln.strip() else ln)
                  for ln in lines[region_start:region_end]]
        if not region:
            region = [indent + "    pass\n"]
        new = (lines[:acq.lineno - 1]
               + [f"{indent}with {recv}:\n"]
               + region
               + lines[rel.end_lineno:])
        return "".join(new), Fix(
            "with-lock", acq.lineno,
            f"{recv}.acquire()/.release() pair -> 'with {recv}:'")
    return source, None


def _fix_lock_pairs(source: str) -> Tuple[str, List[Fix]]:
    fixes: List[Fix] = []
    while True:
        source, fix = _fix_one_pair(source)
        if fix is None:
            return source, fixes
        fixes.append(fix)


# -- entry point ------------------------------------------------------------

def fix_source(source: str, relpath: str, declared: Set[str]
               ) -> Tuple[str, List[Fix]]:
    """Apply every mechanical fixer → (fixed source, applied fixes).
    ``declared`` is the env-knob table (``mxlint.declared_knobs``)."""
    out, fixes = _fix_env_reads(source, relpath, declared)
    out, lock_fixes = _fix_lock_pairs(out)
    return out, fixes + lock_fixes
