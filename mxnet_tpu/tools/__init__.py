"""Command-line tools (reference: tools/ — im2rec, launch.py; SURVEY.md
L12)."""
