"""Evaluation metrics.

Reference parity: python/mxnet/metric.py (SURVEY.md §2.5) — EvalMetric base
(update/get/reset, name-value pairs), Accuracy, TopKAccuracy, F1, MAE/MSE/
RMSE, CrossEntropy, Perplexity, Composite, custom via ``mx.metric.create``.
``.get()`` syncs device values to host exactly like the reference.
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence

import numpy as _np

from .base import MXNetError

__all__ = ["EvalMetric", "Accuracy", "TopKAccuracy", "F1", "MAE", "MSE",
           "RMSE", "CrossEntropy", "Perplexity", "Loss",
           "NegativeLogLikelihood", "PearsonCorrelation", "MCC",
           "CompositeEvalMetric", "CustomMetric", "create", "np"]

_registry: Dict[str, type] = {}


def register(klass):
    _registry[klass.__name__.lower()] = klass
    return klass


def create(metric, *args, **kwargs) -> "EvalMetric":
    if isinstance(metric, EvalMetric):
        return metric
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, (list, tuple)):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m, *args, **kwargs))
        return composite
    name = str(metric).lower()
    aliases = {"acc": "accuracy", "ce": "crossentropy",
               "top_k_accuracy": "topkaccuracy",
               "top_k_acc": "topkaccuracy"}
    name = aliases.get(name, name)
    if name not in _registry:
        raise MXNetError(f"unknown metric {metric!r}")
    return _registry[name](*args, **kwargs)


def _to_np(x) -> _np.ndarray:
    if hasattr(x, "asnumpy"):
        return x.asnumpy()  # mxlint: disable=hidden-host-sync — metric ingestion boundary: EvalMetric.update computes on host numpy by contract, and callers hand it outputs they are about to read anyway (eval loop, not the step path)
    return _np.asarray(x)


def _as_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def reset(self) -> None:
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds) -> None:
        raise NotImplementedError

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", **kwargs):
        super().__init__(name, **kwargs)
        self.axis = axis

    def update(self, labels, preds) -> None:
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_np(label)
            pred = _to_np(pred)
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype(_np.int32).flatten()
            label = label.astype(_np.int32).flatten()
            if pred.shape != label.shape:
                raise MXNetError(f"shape mismatch {pred.shape} vs "
                                 f"{label.shape}")
            self.sum_metric += float((pred == label).sum())
            self.num_inst += len(pred)


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kwargs):
        super().__init__(f"{name}_{top_k}", **kwargs)
        self.top_k = top_k

    def update(self, labels, preds) -> None:
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_np(label).astype(_np.int32)
            pred = _to_np(pred)
            topk = _np.argsort(-pred, axis=-1)[..., :self.top_k]
            hit = (topk == label.reshape(label.shape + (1,))).any(axis=-1)
            self.sum_metric += float(hit.sum())
            self.num_inst += hit.size


@register
class F1(EvalMetric):
    """Binary F1.  average='macro' means the mean of per-update F1 scores
    (reference semantics); 'micro' pools global tp/fp/fn counts."""

    def __init__(self, name="f1", average="macro", **kwargs):
        super().__init__(name, **kwargs)
        self.average = average
        self.reset()

    def reset(self):
        super().reset()
        self._tp = self._fp = self._fn = 0.0

    @staticmethod
    def _f1(tp, fp, fn):
        prec = tp / max(tp + fp, 1e-12)
        rec = tp / max(tp + fn, 1e-12)
        return 2 * prec * rec / max(prec + rec, 1e-12)

    def update(self, labels, preds) -> None:
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_np(label).astype(_np.int32).flatten()
            pred = _to_np(pred)
            if pred.ndim > 1:
                pred = pred.argmax(axis=-1)
            pred = pred.astype(_np.int32).flatten()
            tp = float(((pred == 1) & (label == 1)).sum())
            fp = float(((pred == 1) & (label == 0)).sum())
            fn = float(((pred == 0) & (label == 1)).sum())
            if self.average == "macro":
                self.sum_metric += self._f1(tp, fp, fn)
                self.num_inst += 1
            else:
                self._tp += tp
                self._fp += fp
                self._fn += fn
                self.num_inst += 1

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        if self.average == "macro":
            return (self.name, self.sum_metric / self.num_inst)
        return (self.name, self._f1(self._tp, self._fp, self._fn))


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds) -> None:
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_np(label)
            pred = _to_np(pred)
            self.sum_metric += float(_np.abs(label - pred).mean())
            self.num_inst += 1


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds) -> None:
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_np(label)
            pred = _to_np(pred)
            self.sum_metric += float(((label - pred) ** 2).mean())
            self.num_inst += 1


@register
class RMSE(EvalMetric):
    def __init__(self, name="rmse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds) -> None:
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_np(label)
            pred = _to_np(pred)
            self.sum_metric += float(_np.sqrt(((label - pred) ** 2).mean()))
            self.num_inst += 1


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds) -> None:
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_np(label).astype(_np.int32).flatten()
            pred = _to_np(pred)
            prob = pred[_np.arange(label.shape[0]), label]
            self.sum_metric += float((-_np.log(prob + self.eps)).sum())
            self.num_inst += label.shape[0]


@register
class NegativeLogLikelihood(EvalMetric):
    """reference metric.py NegativeLogLikelihood: mean -log p(label)."""

    def __init__(self, eps=1e-12, name="nll-loss", **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds) -> None:
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_np(label).astype(_np.int32).flatten()
            pred = _to_np(pred).reshape(label.shape[0], -1)
            prob = pred[_np.arange(label.shape[0]), label]
            self.sum_metric += float((-_np.log(prob + self.eps)).sum())
            self.num_inst += label.shape[0]


@register
class PearsonCorrelation(EvalMetric):
    """reference metric.py PearsonCorrelation — streaming over batches via
    accumulated moments (the reference's updated 1.6 form, which unlike
    per-batch averaging is exact over the whole stream)."""

    def __init__(self, name="pearsonr", **kwargs):
        super().__init__(name, **kwargs)
        self.reset()

    def reset(self) -> None:
        super().reset()
        self._n = 0
        self._sx = self._sy = self._sxx = self._syy = self._sxy = 0.0

    def update(self, labels, preds) -> None:
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            x = _to_np(label).astype(_np.float64).ravel()
            y = _to_np(pred).astype(_np.float64).ravel()
            self._n += x.size
            self._sx += x.sum()
            self._sy += y.sum()
            self._sxx += (x * x).sum()
            self._syy += (y * y).sum()
            self._sxy += (x * y).sum()
        n = self._n
        if n == 0:
            return                     # no data yet: metric stays nan
        self.num_inst = 1
        cov = self._sxy - self._sx * self._sy / n
        vx = self._sxx - self._sx ** 2 / n
        vy = self._syy - self._sy ** 2 / n
        denom = _np.sqrt(max(vx * vy, 1e-24))
        self.sum_metric = float(cov / denom)


@register
class MCC(EvalMetric):
    """reference metric.py MCC — binary Matthews correlation coefficient
    from streaming confusion counts."""

    def __init__(self, name="mcc", **kwargs):
        super().__init__(name, **kwargs)
        self.reset()

    def reset(self) -> None:
        super().reset()
        self._tp = self._tn = self._fp = self._fn = 0

    def update(self, labels, preds) -> None:
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            y = _to_np(label).astype(_np.int32).ravel()
            p = _to_np(pred)
            yhat = (p.reshape(y.shape[0], -1).argmax(-1)
                    if p.ndim > 1 and p.shape[-1] > 1
                    else (p.ravel() > 0.5).astype(_np.int32))
            self._tp += int(((yhat == 1) & (y == 1)).sum())
            self._tn += int(((yhat == 0) & (y == 0)).sum())
            self._fp += int(((yhat == 1) & (y == 0)).sum())
            self._fn += int(((yhat == 0) & (y == 1)).sum())
        tp, tn, fp, fn = self._tp, self._tn, self._fp, self._fn
        denom = _np.sqrt(float(tp + fp) * (tp + fn) * (tn + fp) * (tn + fn))
        self.num_inst = 1
        self.sum_metric = ((tp * tn - fp * fn) / denom) if denom else 0.0


@register
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 **kwargs):
        super().__init__(name, **kwargs)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds) -> None:
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_np(label).astype(_np.int32).flatten()
            pred = _to_np(pred).reshape(-1, _to_np(pred).shape[-1])
            prob = pred[_np.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = label == self.ignore_label
                prob = _np.where(ignore, 1.0, prob)
                num = (~ignore).sum()
            else:
                num = label.shape[0]
            self.sum_metric += float(-_np.log(_np.maximum(prob, 1e-12)).sum())
            self.num_inst += int(num)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register
class Loss(EvalMetric):
    def __init__(self, name="loss", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, _, preds) -> None:
        for pred in _as_list(preds):
            loss = _to_np(pred)
            self.sum_metric += float(loss.sum())
            self.num_inst += loss.size


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", **kwargs):
        super().__init__(name, **kwargs)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric) -> None:
        self.metrics.append(create(metric))

    def update(self, labels, preds) -> None:
        for m in self.metrics:
            m.update(labels, preds)

    def reset(self) -> None:
        for m in getattr(self, "metrics", []):
            m.reset()

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.append(n)
            values.append(v)
        return (names, values)


class CustomMetric(EvalMetric):
    def __init__(self, feval, name="custom", allow_extra_outputs=False,
                 **kwargs):
        super().__init__(f"custom({name})", **kwargs)
        self._feval = feval

    def update(self, labels, preds) -> None:
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            v = self._feval(_to_np(label), _to_np(pred))
            if isinstance(v, tuple):
                s, n = v
                self.sum_metric += s
                self.num_inst += n
            else:
                self.sum_metric += v
                self.num_inst += 1


def np(numpy_feval, name="custom", allow_extra_outputs=False):
    """Wrap a numpy feval into a metric (reference: mx.metric.np)."""
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = getattr(numpy_feval, "__name__", name)
    return CustomMetric(feval, name=feval.__name__,
                        allow_extra_outputs=allow_extra_outputs)
