"""Optimizers.

Reference parity: python/mxnet/optimizer/optimizer.py (SURVEY.md §2.5) —
registry (`mx.optimizer.create``), SGD with momentum + multi_precision
(fp32 master weights), Adam/NAG/RMSProp/AdaGrad/Ftrl/Signum, per-param
lr_mult/wd_mult, lr scheduling, and the ``Updater`` wrapper the KVStore uses
server-side.  Each update step executes as one fused XLA computation via the
registered ``*_update`` ops; the learning rate is a runtime input so
schedules never recompile.
"""
from __future__ import annotations

import math
from typing import Any, Dict, Optional

import numpy as _np

from .base import MXNetError
from .ndarray import NDArray, zeros as nd_zeros, array as nd_array
from .ndarray.register import invoke_by_name

__all__ = ["Optimizer", "SGD", "NAG", "Adam", "AdaGrad", "RMSProp", "Ftrl",
           "Signum", "AdaDelta", "AdamW", "LARS", "LBSGD", "Adamax",
           "Nadam", "SGLD", "DCASGD", "FTML", "LAMB", "register",
           "create", "Updater", "get_updater"]

_registry: Dict[str, type] = {}


def _is_low_precision(dtype) -> bool:
    """fp16 or bfloat16 — the dtypes multi_precision keeps fp32 masters for
    (bf16 is the TPU-native low precision; fp16 kept for parity)."""
    return dtype == _np.float16 or \
        getattr(_np.dtype(dtype), "name", "") == "bfloat16"


def register(klass):
    _registry[klass.__name__.lower()] = klass
    return klass


def create(name, **kwargs) -> "Optimizer":
    if isinstance(name, Optimizer):
        return name
    if name.lower() not in _registry:
        raise MXNetError(f"unknown optimizer {name!r}")
    return _registry[name.lower()](**kwargs)


class Optimizer:
    """Base optimizer with per-index lr/wd multipliers and update counting."""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 multi_precision=False, param_dict=None, begin_num_update=0,
                 **kwargs):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.param_idx2name = param_idx2name or {}
        self.param_dict = param_dict or {}
        self.num_update = begin_num_update
        self.begin_num_update = begin_num_update
        self._index_update_count: Dict[int, int] = {}
        self.idx2name = self.param_idx2name
        self.lr_mult: Dict[Any, float] = {}
        self.wd_mult: Dict[Any, float] = {}
        # >1 enables multi-tensor apply in Trainer (reference:
        # MXNET_OPTIMIZER_AGGREGATION_SIZE); only optimizers that
        # implement update_multi (SGD) honor it
        self.aggregate_num = 0

    # -- bookkeeping -------------------------------------------------------
    def extra_state(self):
        """Scalar optimizer state beyond per-param tensors (e.g. Nadam's
        momentum-schedule product) — serialized by Updater.get_states
        (dump_optimizer=True) so time-dependent optimizers resume
        exactly.  Return None when there is nothing extra."""
        return None

    def set_extra_state(self, extra) -> None:
        pass

    def _update_count(self, index) -> None:
        cnt = self._index_update_count.get(index, self.begin_num_update)
        self._index_update_count[index] = cnt + 1
        self.num_update = max(self.num_update, self._index_update_count[index])

    def set_learning_rate(self, lr: float) -> None:
        if self.lr_scheduler is not None:
            raise MXNetError("cannot set lr directly when lr_scheduler is set")
        self.lr = lr

    @property
    def learning_rate(self) -> float:
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def _get_lr(self, index) -> float:
        lr = self.learning_rate
        param = self.param_dict.get(index)
        if param is not None:
            lr *= param.lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.param_idx2name:
            lr *= self.lr_mult.get(self.param_idx2name[index], 1.0)
        return lr

    def _get_wd(self, index) -> float:
        wd = self.wd
        param = self.param_dict.get(index)
        if param is not None:
            wd *= param.wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.param_idx2name:
            wd *= self.wd_mult.get(self.param_idx2name[index], 1.0)
        return wd

    def set_lr_mult(self, args_lr_mult: Dict) -> None:
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult: Dict) -> None:
        self.wd_mult = dict(args_wd_mult)

    # -- interface ---------------------------------------------------------
    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and _is_low_precision(weight.dtype):
            w32 = weight.astype("float32")
            return (self.create_state(index, w32), w32)
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        """Generic multi-precision path: run the update on the fp32 master
        weight, then downcast into the live weight (optimizers with a fused
        mp kernel, like SGD, override this)."""
        from .sparse import BaseSparseNDArray
        if isinstance(grad, BaseSparseNDArray) and self.multi_precision:
            grad = grad.todense()
        if self.multi_precision and isinstance(state, tuple) and \
                len(state) == 2 and isinstance(state[1], NDArray) and \
                state[1].dtype == _np.float32 and \
                weight.dtype != _np.float32:
            inner, w32 = state
            self.update(index, w32, grad.astype("float32"), inner)
            weight._set_data(w32._read().astype(weight.dtype))
        else:
            self.update(index, weight, grad, state)

    def _common_kwargs(self, index) -> Dict[str, Any]:
        kw = {"wd": self._get_wd(index), "rescale_grad": self.rescale_grad}
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        return kw

    def _lr_nd(self, index, weight, scale: float = 1.0) -> NDArray:
        # must live on the weight's device: mixed-device jit inputs are an
        # error on real TPU (CPU test meshes mask this)
        return nd_array(_np.float32(self._get_lr(index) * scale),
                        ctx=weight.context)


@register
class SGD(Optimizer):
    """SGD with momentum and multi-precision master weights."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update
        from .base import get_env
        self.aggregate_num = int(get_env(
            "MXNET_OPTIMIZER_AGGREGATION_SIZE"))

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd_zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        from .sparse import RowSparseNDArray
        if isinstance(grad, RowSparseNDArray):
            if self.lazy_update:
                return self._update_row_sparse(index, weight, grad, state)
            grad = grad.todense()      # reference: lazy_update=False path
        self._update_count(index)
        kw = self._common_kwargs(index)
        lr = self._lr_nd(index, weight)
        if self.momentum == 0.0:
            invoke_by_name("sgd_update", [weight, grad, lr], kw, out=weight)
        else:
            kw["momentum"] = self.momentum
            invoke_by_name("sgd_mom_update", [weight, grad, state, lr], kw,
                           out=[weight, state])

    def _update_row_sparse(self, index, weight, grad, state):
        """Lazy update: touch only the rows present in the row_sparse grad
        (reference: sgd_update/sgd_mom_update row_sparse kernels with
        lazy_update=True — src/operator/optimizer_op.cc).  Pure scatter on
        the dense weight: HBM traffic ∝ touched rows."""
        import jax.numpy as jnp
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        rows = jnp.asarray(grad.indices)
        g = jnp.asarray(grad.data) * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        w = weight._read()
        g = g + wd * w[rows]
        if self.momentum == 0.0:
            weight._set_data(w.at[rows].add(-lr * g))
        else:
            m = state._read()
            m_rows = self.momentum * m[rows] - lr * g
            state._set_data(m.at[rows].set(m_rows))
            weight._set_data(w.at[rows].add(m_rows))

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and isinstance(state, tuple) and \
                len(state) == 2 and isinstance(state[1], NDArray):
            mom, w32 = state
            self._update_count(index)
            kw = self._common_kwargs(index)
            kw["momentum"] = self.momentum
            if mom is None:
                mom = nd_zeros(w32.shape, ctx=w32.context, dtype=w32.dtype)
            lr = self._lr_nd(index, weight)
            invoke_by_name("mp_sgd_mom_update",
                           [weight, grad, mom, w32, lr], kw,
                           out=[weight, mom, w32])
        else:
            self.update(index, weight, grad, state)

    def update_multi(self, indices, weights, grads, states):
        """Fused multi-tensor apply: ONE Pallas launch updates the whole
        group (reference multi_sgd_update family; kernels/multi_sgd.py).

        Falls back per-tensor for sparse grads, mixed dtypes, or shapes
        the fused path cannot batch.
        """
        from .sparse import BaseSparseNDArray
        dt = weights[0].dtype
        mp = (self.multi_precision and isinstance(states[0], tuple) and
              len(states[0]) == 2 and isinstance(states[0][1], NDArray))
        fallback = (any(isinstance(g, BaseSparseNDArray) for g in grads)
                    or any(w.dtype != dt for w in weights)
                    or (mp and self.momentum == 0.0)
                    or (mp and any(not isinstance(s, tuple)
                                   for s in states)))
        if fallback:
            for i, w, g, s in zip(indices, weights, grads, states):
                self.update_multi_precision(i, w, g, s)
            return
        for i in indices:
            self._update_count(i)
        ctx = weights[0].context
        lrs = nd_array(_np.array([self._get_lr(i) for i in indices],
                                 _np.float32), ctx=ctx)
        wds = nd_array(_np.array([self._get_wd(i) for i in indices],
                                 _np.float32), ctx=ctx)
        kw: Dict[str, Any] = {"rescale_grad": self.rescale_grad,
                              "num_weights": len(indices)}
        # Mosaic vs interpret must be decided OUTSIDE the trace (a traced
        # array has no device); key it on the concrete weight context
        try:
            kw["interpret"] = ctx.device.platform not in ("tpu", "axon")
        except Exception:
            pass
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        data: list = []
        out: list = []
        if mp:
            kw["momentum"] = self.momentum
            for w, g, s in zip(weights, grads, states):
                mom, w32 = s
                if mom is None:
                    mom = nd_zeros(w32.shape, ctx=w32.context,
                                   dtype=w32.dtype)
                data.extend((w, g, mom, w32))
                out.extend((w, mom, w32))
            invoke_by_name("multi_mp_sgd_mom_update", data + [lrs, wds],
                           kw, out=out)
        elif self.momentum != 0.0:
            kw["momentum"] = self.momentum
            for w, g, s in zip(weights, grads, states):
                data.extend((w, g, s))
                out.extend((w, s))
            invoke_by_name("multi_sgd_mom_update", data + [lrs, wds], kw,
                           out=out)
        else:
            for w, g in zip(weights, grads):
                data.extend((w, g))
                out.append(w)
            invoke_by_name("multi_sgd_update", data + [lrs, wds], kw,
                           out=out)


@register
class NAG(Optimizer):
    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd_zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        kw["momentum"] = self.momentum
        lr = self._lr_nd(index, weight)
        if state is None:
            invoke_by_name("sgd_update", [weight, grad, lr],
                           self._common_kwargs(index), out=weight)
        else:
            invoke_by_name("nag_mom_update", [weight, grad, state, lr], kw,
                           out=[weight, state])


@register
class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (nd_zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                nd_zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        from .sparse import RowSparseNDArray
        if isinstance(grad, RowSparseNDArray):
            if self.lazy_update:
                return self._update_row_sparse(index, weight, grad, state)
            grad = grad.todense()
        self._update_count(index)
        t = self._index_update_count[index]
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        lr_t = self._get_lr(index) * math.sqrt(coef2) / coef1
        mean, var = state
        kw = self._common_kwargs(index)
        kw.update(beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon)
        lr = nd_array(_np.float32(lr_t), ctx=weight.context)
        invoke_by_name("adam_update", [weight, grad, mean, var, lr], kw,
                       out=[weight, mean, var])

    def _update_row_sparse(self, index, weight, grad, state):
        """Lazy Adam: mean/var/weight touched only on the grad's rows
        (reference adam_update row_sparse kernel with lazy_update=True)
        — untouched rows keep their moments frozen, so the update cost
        scales with touched rows, not vocab.  Mirrors
        parallel/optim.py's in-graph row path formula for formula."""
        import jax.numpy as jnp
        self._update_count(index)
        t = self._index_update_count[index]
        lr_t = self._get_lr(index) * \
            math.sqrt(1.0 - self.beta2 ** t) / (1.0 - self.beta1 ** t)
        wd = self._get_wd(index)
        rows = jnp.asarray(grad.indices)
        g = jnp.asarray(grad.data) * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        w = weight._read()
        g = g + wd * w[rows]
        mean, var = state
        m, v = mean._read(), var._read()
        m_rows = self.beta1 * m[rows] + (1.0 - self.beta1) * g
        v_rows = self.beta2 * v[rows] + (1.0 - self.beta2) * jnp.square(g)
        mean._set_data(m.at[rows].set(m_rows))
        var._set_data(v.at[rows].set(v_rows))
        weight._set_data(w.at[rows].add(
            -lr_t * m_rows / (jnp.sqrt(v_rows) + self.epsilon)))


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return nd_zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        kw["epsilon"] = self.float_stable_eps
        lr = self._lr_nd(index, weight)
        invoke_by_name("adagrad_update", [weight, grad, state, lr], kw,
                       out=[weight, state])


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.epsilon = epsilon
        self.centered = centered
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        mk = lambda: nd_zeros(weight.shape, ctx=weight.context,
                              dtype=weight.dtype)
        if self.centered:
            return (mk(), mk(), mk())   # n, g_avg, delta (rmspropalex)
        return mk()

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        kw.update(gamma1=self.gamma1, epsilon=self.epsilon)
        if self.clip_weights is not None:
            kw["clip_weights"] = self.clip_weights
        lr = self._lr_nd(index, weight)
        if self.centered:
            n, g_avg, delta = state
            kw["gamma2"] = self.gamma2
            invoke_by_name("rmspropalex_update",
                           [weight, grad, n, g_avg, delta, lr], kw,
                           out=[weight, n, g_avg, delta])
        else:
            invoke_by_name("rmsprop_update", [weight, grad, state, lr], kw,
                           out=[weight, state])


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (nd_zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                nd_zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        z, n = state
        kw = self._common_kwargs(index)
        kw.update(lamda1=self.lamda1, beta=self.beta)
        lr = self._lr_nd(index, weight)
        invoke_by_name("ftrl_update", [weight, grad, z, n, lr], kw,
                       out=[weight, z, n])


@register
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd_zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        lr = self._lr_nd(index, weight)
        if state is None:
            invoke_by_name("signsgd_update", [weight, grad, lr], kw,
                           out=weight)
        else:
            kw.update(momentum=self.momentum, wd_lh=self.wd_lh)
            invoke_by_name("signum_update", [weight, grad, state, lr], kw,
                           out=[weight, state])


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.9, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (nd_zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                nd_zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        # composed from primitive ops (no fused kernel in the reference either)
        acc_g, acc_d = state
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            from .ndarray import clip as nd_clip
            g = nd_clip(g, a_min=-self.clip_gradient,
                        a_max=self.clip_gradient)
        from .ndarray import sqrt as nd_sqrt
        acc_g_new = self.rho * acc_g + (1 - self.rho) * g * g
        delta = nd_sqrt(acc_d + self.epsilon) / \
            nd_sqrt(acc_g_new + self.epsilon) * g
        acc_d_new = self.rho * acc_d + (1 - self.rho) * delta * delta
        acc_g._set_data(acc_g_new._read())
        acc_d._set_data(acc_d_new._read())
        weight._set_data((weight - delta - wd * weight)._read())


@register
class AdamW(Optimizer):
    """Adam with decoupled weight decay (reference:
    src/operator/contrib/adamw.cc + python contrib.optimizer.AdamW).

    ``wd`` is applied to the weight directly (scaled by ``eta``), outside
    the adaptive preconditioner; bias correction is folded into the lr
    passed to the fused op, as the reference python wrapper does.
    """

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, eta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon, self.eta = \
            beta1, beta2, epsilon, eta

    def create_state(self, index, weight):
        import numpy as np
        return (nd_zeros(weight.shape, ctx=weight.context,
                         dtype=np.float32),
                nd_zeros(weight.shape, ctx=weight.context,
                         dtype=np.float32))

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and _is_low_precision(weight.dtype):
            w32 = weight.astype(_np.float32)
            return (self.create_state(index, w32), w32)
        return self.create_state(index, weight)

    def _corrected_lr(self, index):
        t = self._index_update_count[index]
        return self._get_lr(index) * \
            math.sqrt(1.0 - self.beta2 ** t) / (1.0 - self.beta1 ** t)

    def _kw(self, index):
        # decoupled decay is lr-scaled (w -= lr*wd*w, the torch/Loshchilov
        # convention); the op applies eta*wd_in*w, so fold the PLAIN lr
        # into wd_in while the op's lr input carries bias correction
        kw = {"beta1": self.beta1, "beta2": self.beta2,
              "epsilon": self.epsilon,
              "wd": self._get_wd(index) * self._get_lr(index),
              "eta": self.eta, "rescale_grad": self.rescale_grad}
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        return kw

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._kw(index)
        lr = nd_array(_np.float32(self._corrected_lr(index)),
                      ctx=weight.context)
        mean, var = state
        invoke_by_name("adamw_update", [weight, grad, mean, var, lr], kw,
                       out=[weight, mean, var])

    def update_multi_precision(self, index, weight, grad, state):
        # mp state is ((mean, var), w32); plain fp32 state is (mean, var)
        # — the inner-tuple check keeps them apart
        if self.multi_precision and isinstance(state, tuple) and \
                len(state) == 2 and isinstance(state[0], tuple) and \
                isinstance(state[1], NDArray):
            (mean, var), w32 = state
            self._update_count(index)
            kw = self._kw(index)
            lr = nd_array(_np.float32(self._corrected_lr(index)),
                          ctx=weight.context)
            invoke_by_name("mp_adamw_update",
                           [weight, grad, mean, var, w32, lr], kw,
                           out=[weight, mean, var, w32])
        else:
            self.update(index, weight, grad, state)


@register
class LARS(Optimizer):
    """Layer-wise Adaptive Rate Scaling (reference: the LARS optimizer +
    multi_lars contrib kernels that landed for large-batch ResNet;
    You et al. 2017).

    Per layer: ``local_lr = eta * ||w|| / (||g*rescale|| + wd*||w|| + eps)``
    computed ON DEVICE by the ``lars_trust`` op (no host sync), folded into
    the lr input of the fused sgd(_mom) update.
    """

    def __init__(self, momentum=0.9, eta=0.001, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.eta = eta
        self.epsilon = epsilon

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd_zeros(weight.shape, ctx=weight.context,
                        dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        trust = invoke_by_name(
            "lars_trust", [weight, grad,
                           nd_array(_np.float32(self._get_wd(index)),
                                    ctx=weight.context)],
            {"eta": self.eta, "epsilon": self.epsilon,
             "rescale_grad": self.rescale_grad})
        lr = self._lr_nd(index, weight) * trust
        if self.momentum == 0.0:
            invoke_by_name("sgd_update", [weight, grad, lr], kw, out=weight)
        else:
            kw["momentum"] = self.momentum
            invoke_by_name("sgd_mom_update", [weight, grad, state, lr], kw,
                           out=[weight, state])


@register
class LBSGD(Optimizer):
    """Large-Batch SGD with warmup + LARS trust scaling (reference:
    python/mxnet/optimizer/optimizer.py LBSGD).

    warmup_strategy: 'linear'/'power2'/'sqrt' ramp the lr over
    ``warmup_epochs``; 'lars' applies the layer-wise trust ratio every
    step (the reference's default large-batch recipe).
    """

    def __init__(self, momentum=0.0, multi_precision=False,
                 warmup_strategy="linear", warmup_epochs=5,
                 batch_scale=1, updates_per_epoch=32, begin_epoch=0,
                 num_epochs=60, eta=0.001, **kwargs):
        super().__init__(multi_precision=multi_precision, **kwargs)
        self.momentum = momentum
        self.warmup_strategy = warmup_strategy
        self.warmup_epochs = warmup_epochs
        self.batch_scale = batch_scale
        self.updates_per_epoch = max(1, updates_per_epoch)
        self.begin_epoch = begin_epoch
        self.num_epochs = num_epochs
        self.eta = eta
        self.epsilon = 1e-8

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd_zeros(weight.shape, ctx=weight.context,
                        dtype=weight.dtype)

    def _warmup_scale(self, index) -> float:
        t = self._index_update_count[index]
        warm_T = self.warmup_epochs * self.updates_per_epoch
        if self.warmup_strategy not in ("linear", "power2", "sqrt") or \
                t >= warm_T:
            return 1.0
        frac = t / warm_T
        if self.warmup_strategy == "linear":
            return frac
        if self.warmup_strategy == "power2":
            return frac * frac
        return math.sqrt(frac)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        scale = self._warmup_scale(index)
        lr = self._lr_nd(index, weight, scale=scale)
        if self.warmup_strategy == "lars":
            trust = invoke_by_name(
                "lars_trust", [weight, grad,
                               nd_array(_np.float32(self._get_wd(index)),
                                        ctx=weight.context)],
                {"eta": self.eta, "epsilon": self.epsilon,
                 "rescale_grad": self.rescale_grad})
            lr = lr * trust
        if self.momentum == 0.0:
            invoke_by_name("sgd_update", [weight, grad, lr], kw, out=weight)
        else:
            kw["momentum"] = self.momentum
            invoke_by_name("sgd_mom_update", [weight, grad, state, lr], kw,
                           out=[weight, state])


@register
class FTML(Optimizer):
    """Follow The Moving Leader (reference: src/operator/optimizer_op.cc
    ftml_update; python/mxnet/optimizer FTML).  One fused XLA update per
    parameter via the ``ftml_update`` op."""

    def __init__(self, learning_rate=0.0025, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        z = nd_zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)
        d = nd_zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)
        v = nd_zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)
        return (d, v, z)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        d, v, z = state
        kw = {"beta1": self.beta1, "beta2": self.beta2,
              "epsilon": self.epsilon, "t": t,
              "wd": self._get_wd(index),
              "rescale_grad": self.rescale_grad}
        if self.clip_gradient is not None:
            kw["clip_grad"] = self.clip_gradient
        lr = self._lr_nd(index, weight)
        invoke_by_name("ftml_update", [weight, grad, d, v, z, lr], kw,
                       out=[weight, d, v, z])


@register
class LAMB(Optimizer):
    """Layer-wise Adaptive Moments for Batch training (reference:
    src/operator/optimizer_op.cc lamb_update_phase1/phase2; python
    optimizer LAMB).  Phase 1 computes the adam-style direction, phase 2
    applies it scaled by the layerwise trust ratio ||w||/||direction||."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return (nd_zeros(weight.shape, ctx=weight.context,
                         dtype=_np.float32),
                nd_zeros(weight.shape, ctx=weight.context,
                         dtype=_np.float32))

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and _is_low_precision(weight.dtype):
            w32 = weight.astype(_np.float32)
            return (self.create_state(index, w32), w32)
        return self.create_state(index, weight)

    def _phase_kwargs(self, index):
        kw = {"beta1": self.beta1, "beta2": self.beta2,
              "epsilon": self.epsilon,
              "t": self._index_update_count[index],
              "bias_correction": self.bias_correction,
              "wd": self._get_wd(index),
              "rescale_grad": self.rescale_grad}
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        return kw

    def _phase2_kwargs(self):
        kw = {}
        if self.lower_bound is not None:
            kw["lower_bound"] = self.lower_bound
        if self.upper_bound is not None:
            kw["upper_bound"] = self.upper_bound
        return kw

    def update(self, index, weight, grad, state):
        self._update_count(index)
        mean, var = state
        d = invoke_by_name("lamb_update_phase1", [weight, grad, mean, var],
                           self._phase_kwargs(index))
        direction, m_new, v_new = d
        mean._set_data(m_new._read())
        var._set_data(v_new._read())
        from .ndarray import norm as _nd_norm
        r1 = _nd_norm(weight)
        r2 = _nd_norm(direction)
        lr = self._lr_nd(index, weight)
        invoke_by_name("lamb_update_phase2",
                       [weight, direction, r1, r2, lr],
                       self._phase2_kwargs(), out=weight)

    def update_multi_precision(self, index, weight, grad, state):
        if not (self.multi_precision and _is_low_precision(weight.dtype)):
            return self.update(index, weight, grad, state)
        self._update_count(index)
        (mean, var), w32 = state
        d = invoke_by_name("mp_lamb_update_phase1",
                           [weight, grad, mean, var, w32],
                           self._phase_kwargs(index))
        direction, m_new, v_new = d
        mean._set_data(m_new._read())
        var._set_data(v_new._read())
        from .ndarray import norm as _nd_norm
        r1 = _nd_norm(w32)
        r2 = _nd_norm(direction)
        lr = self._lr_nd(index, w32)
        invoke_by_name("mp_lamb_update_phase2",
                       [weight, direction, r1, r2, w32, lr],
                       self._phase2_kwargs(), out=[weight, w32])


class Updater:
    """Callable wrapper used by KVStore to run the optimizer server-side
    (reference: mx.optimizer.get_updater)."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states: Dict[Any, Any] = {}
        self.states_synced: Dict[Any, bool] = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = \
                self.optimizer.create_state_multi_precision(index, weight)
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def get_states(self, dump_optimizer=False):
        """Serialize updater state; with dump_optimizer also the update
        counters (num_update / per-index counts) so time-dependent
        optimizers (Adam bias correction, lr schedules) resume correctly."""
        import pickle
        blob = {"states": {k: _states_to_np(v)
                           for k, v in self.states.items()}}
        if dump_optimizer:
            blob["num_update"] = self.optimizer.num_update
            blob["index_update_count"] = \
                dict(self.optimizer._index_update_count)
            extra = self.optimizer.extra_state()
            if extra is not None:
                blob["optimizer_extra"] = extra
        return pickle.dumps(blob)

    def set_states(self, states) -> None:
        import pickle
        loaded = pickle.loads(states)
        if "states" not in loaded:  # legacy flat format
            loaded = {"states": loaded}
        self.states = {k: _states_from_np(v)
                       for k, v in loaded["states"].items()}
        if "num_update" in loaded:
            self.optimizer.num_update = loaded["num_update"]
            self.optimizer._index_update_count = dict(
                loaded["index_update_count"])
        if "optimizer_extra" in loaded:
            self.optimizer.set_extra_state(loaded["optimizer_extra"])


def _states_to_np(state):
    if state is None:
        return None
    if isinstance(state, tuple):
        return tuple(_states_to_np(s) for s in state)
    # checkpoint serialization boundary (set_states/get_states)
    # mxlint: disable=hidden-host-sync — checkpoint serialization
    return state.asnumpy()


def _states_from_np(state):
    if state is None:
        return None
    if isinstance(state, tuple):
        return tuple(_states_from_np(s) for s in state)
    return nd_array(state)


def get_updater(optimizer: Optimizer) -> Updater:
    return Updater(optimizer)


# ---------------------------------------------------------------------------
# python-composed optimizers (reference optimizer.py implements these from
# primitive ops too — no fused kernels upstream either)
# ---------------------------------------------------------------------------

def _prepped(opt: Optimizer, index, grad, weight, with_wd=True):
    """Python-composed-optimizer gradient prep.  NOTE the order differs
    from the fused kernels' _prep_grad: the reference's python optimizers
    (Adamax/Nadam/...) add wd*weight FIRST and clip the SUM, while its
    C++ update kernels clip first — both conventions are mirrored
    faithfully on their respective paths."""
    g = grad * opt.rescale_grad
    if with_wd:
        wd = opt._get_wd(index)
        if wd:
            g = g + wd * weight
    if opt.clip_gradient is not None:
        from .ndarray import clip as nd_clip
        g = nd_clip(g, a_min=-opt.clip_gradient, a_max=opt.clip_gradient)
    return g


@register
class Adamax(Optimizer):
    """AdaMax (reference optimizer.py Adamax — Adam with the ∞-norm)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def create_state(self, index, weight):
        return (nd_zeros(weight.shape, ctx=weight.context,
                         dtype=weight.dtype),
                nd_zeros(weight.shape, ctx=weight.context,
                         dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        from .ndarray import abs as nd_abs, maximum as nd_maximum
        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index) / (1.0 - self.beta1 ** t)
        g = _prepped(self, index, grad, weight)
        m, u = state
        m_new = self.beta1 * m + (1.0 - self.beta1) * g
        u_new = nd_maximum(self.beta2 * u, nd_abs(g))
        m._set_data(m_new._read())
        u._set_data(u_new._read())
        weight._set_data((weight - lr * m_new / (u_new + 1e-8))._read())


@register
class Nadam(Optimizer):
    """Nesterov Adam (reference optimizer.py Nadam — Adam with the
    momentum schedule of Dozat 2016)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def extra_state(self):
        return {"m_schedule": self.m_schedule}

    def set_extra_state(self, extra) -> None:
        self.m_schedule = float(extra["m_schedule"])

    def create_state(self, index, weight):
        return (nd_zeros(weight.shape, ctx=weight.context,
                         dtype=weight.dtype),
                nd_zeros(weight.shape, ctx=weight.context,
                         dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        from .ndarray import sqrt as nd_sqrt
        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index)
        g = _prepped(self, index, grad, weight)
        mu_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (t * self.schedule_decay))
        mu_t1 = self.beta1 * (1.0 - 0.5 * 0.96 **
                              ((t + 1) * self.schedule_decay))
        self.m_schedule = self.m_schedule * mu_t
        m_schedule_next = self.m_schedule * mu_t1
        m, v = state
        m_new = self.beta1 * m + (1.0 - self.beta1) * g
        v_new = self.beta2 * v + (1.0 - self.beta2) * g * g
        g_prime = g / (1.0 - self.m_schedule)
        m_prime = m_new / (1.0 - m_schedule_next)
        v_prime = v_new / (1.0 - self.beta2 ** t)
        m_bar = (1.0 - mu_t) * g_prime + mu_t1 * m_prime
        m._set_data(m_new._read())
        v._set_data(v_new._read())
        weight._set_data(
            (weight - lr * m_bar / (nd_sqrt(v_prime) + self.epsilon))
            ._read())


@register
class SGLD(Optimizer):
    """Stochastic Gradient Langevin Dynamics (reference optimizer.py
    SGLD): gradient step + N(0, sqrt(lr)) noise — the sampling optimizer."""

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        from .ndarray import random as nd_random
        self._update_count(index)
        lr = self._get_lr(index)
        # reference SGLD: clip the raw rescaled gradient; wd*weight rides
        # OUTSIDE the clip (unlike Adamax/Nadam, which clip the sum)
        g = _prepped(self, index, grad, weight, with_wd=False)
        g = g + self._get_wd(index) * weight
        noise = nd_random.normal(0.0, _np.sqrt(lr), shape=weight.shape,
                                 ctx=weight.context, dtype=weight.dtype)
        weight._set_data((weight - 0.5 * lr * g + noise)._read())


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference optimizer.py DCASGD):
    compensates stale gradients with the Taylor term
    ``lambda * g² * (w - w_prev)``."""

    def __init__(self, learning_rate=0.01, momentum=0.0, lamda=0.04,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.lamda = lamda

    def create_state(self, index, weight):
        mom = None if self.momentum == 0.0 else nd_zeros(
            weight.shape, ctx=weight.context, dtype=weight.dtype)
        return (mom, weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        # reference formula: wd rides OUTSIDE the squared Taylor term —
        # only the raw (rescaled/clipped) gradient is squared
        g = _prepped(self, index, grad, weight, with_wd=False)
        wd = self._get_wd(index)
        mom, prev = state
        comp = g + wd * weight + self.lamda * g * g * (weight - prev)
        if mom is None:
            step = -lr * comp
        else:
            mom_new = self.momentum * mom - lr * comp
            mom._set_data(mom_new._read())
            step = mom_new
        prev._set_data(weight._read())
        weight._set_data((weight + step)._read())
