"""Imperative autograd: ``record()`` scopes, tape, ``backward()``.

Reference role: src/imperative/imperative.cc + python/mxnet/autograd.py —
when recording is on, every op invoke appends a tape node; ``Backward`` builds
the gradient graph via the NNVM ``Gradient`` pass and pushes it through the
engine (SURVEY.md §3.2).

TPU-native design: instead of per-op registered ``FGradient`` symbolic
rewrites, each dispatched op is recorded as a ``jax.vjp`` closure — JAX's
tracer derives the backward computation, and the saved residuals live in the
closure exactly like the reference's saved NDArrays on the tape.  ``backward``
is then a reverse-topological walk accumulating cotangents.  The dispatch of
the backward ops is async through XLA just as the reference's was through the
threaded engine.
"""
from __future__ import annotations

import functools
import threading
from typing import Any, List, Optional, Sequence

import numpy as _np

from .base import MXNetError

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "mark_variables", "backward", "grad", "get_symbol",
           "Function"]

_state = threading.local()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
    return _state


def is_recording() -> bool:
    return _st().recording


def is_training() -> bool:
    return _st().training


class _RecordingScope:
    def __init__(self, recording: Optional[bool], training: Optional[bool]):
        self._rec = recording
        self._train = training
        self._prev = None

    def __enter__(self):
        st = _st()
        self._prev = (st.recording, st.training)
        if self._rec is not None:
            st.recording = self._rec
        if self._train is not None:
            st.training = self._train
        return self

    def __exit__(self, *a):
        st = _st()
        st.recording, st.training = self._prev


def record(train_mode: bool = True) -> _RecordingScope:
    """Record operations for gradient computation; sets train mode."""
    return _RecordingScope(True, train_mode)


def pause(train_mode: bool = False) -> _RecordingScope:
    """Suspend recording (e.g. for metric updates, running-stat writes)."""
    return _RecordingScope(False, train_mode)


def train_mode() -> _RecordingScope:
    return _RecordingScope(None, True)


def predict_mode() -> _RecordingScope:
    return _RecordingScope(None, False)


# ---------------------------------------------------------------------------
# Tape
# ---------------------------------------------------------------------------

class TapeNode:
    """One recorded op: a vjp closure + links to producer entries of inputs.

    ``runner_safe`` marks vjp closures produced by register.py's JITTED
    per-op wrapper (stable pytree treedef across calls) — only those may
    ride backward()'s jitted runner.  Bare jax.vjp Partials get a FRESH
    treedef per call (runner jit-cache miss ⇒ recompile every backward —
    round-4 review), and the hybridize CachedOp vjp is already one
    compiled pjit call, so both run direct.
    """
    __slots__ = ("name", "vjp_fn", "parents", "out_avals", "multi_out",
                 "runner_safe")

    def __init__(self, name, vjp_fn, parents, out_avals, multi_out,
                 runner_safe=False):
        self.name = name
        self.vjp_fn = vjp_fn
        self.parents = parents        # list[Optional[AGInfo]] aligned w/ inputs
        self.out_avals = out_avals    # [(shape, dtype)] per output
        self.multi_out = multi_out
        self.runner_safe = runner_safe


class AGInfo:
    """Autograd entry attached to an NDArray (reference: AGInfo on nnvm node)."""
    __slots__ = ("node", "index", "grad", "grad_req", "fresh")

    def __init__(self, node: Optional[TapeNode] = None, index: int = 0,
                 grad=None, grad_req: str = "write"):
        self.node = node
        self.index = index
        self.grad = grad              # NDArray gradient buffer (variables only)
        self.grad_req = grad_req
        self.fresh = True             # 'write' semantics: first accum overwrites

    @property
    def is_variable(self) -> bool:
        return self.grad is not None


def mark_variables(variables, gradients, grad_reqs="write") -> None:
    """Attach gradient buffers to arrays (reference: MXAutogradMarkVariables)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._ag = AGInfo(node=None, index=0, grad=g, grad_req=req)


def _zeros_ct(aval):
    import jax.numpy as jnp
    shape, dtype = aval
    return jnp.zeros(shape, dtype)


def _vjp_runner():
    """Jitted executor for tape-node vjp closures.

    A vjp_fn from jax.vjp is a ``tree_util.Partial`` — its residuals are
    pytree LEAVES, so passing it as an argument lets jit cache one
    compiled backward per (op, shape) signature while fresh residual
    values flow in as ordinary inputs.  Without this, every tape node's
    backward executed primitive-by-primitive through the eager
    interpreter — measured ~1200 µs/node vs ~90 µs for the jitted
    forward dispatch (the round-3 'imperative dispatch is 657 µs/op'
    gap was mostly THIS, on the backward half)."""
    global _vjp_runner_fn
    if _vjp_runner_fn is None:
        import jax
        _vjp_runner_fn = jax.jit(lambda vjp_fn, ct: vjp_fn(ct))
    return _vjp_runner_fn


_vjp_runner_fn = None


def _is_float0(ct) -> bool:
    from jax.dtypes import float0
    return getattr(ct, "dtype", None) == float0


@functools.lru_cache(maxsize=256)
def _ones_seed_cached(shape, dtype_str):
    import jax.numpy as jnp
    return jnp.ones(shape, dtype_str)


def _ones_seed(shape, dtype_str):
    """Default head cotangent: eagerly building jnp.ones dispatches two
    primitives EVERY backward, and losses reuse the same (shape, dtype)
    every step — so SMALL seeds (the scalar/loss case) are cached.  Large
    heads get a fresh buffer: pinning up to 256 arbitrary activations for
    the process lifetime could hold gigabytes of device memory."""
    n = 1
    for d in shape:
        n *= d
    if n <= 16384:
        return _ones_seed_cached(shape, dtype_str)
    import jax.numpy as jnp
    return jnp.ones(shape, dtype_str)


def backward(heads: Sequence, head_grads=None, retain_graph: bool = False,
             train_mode: bool = True) -> None:
    """Run backward from ``heads`` accumulating into variables' ``.grad``.

    Reference: Imperative::Backward (SURVEY.md §3.2) — builds the gradient
    graph from the tape and executes it through the engine; here each tape
    node's ``jax.vjp`` closure is invoked in reverse topological order and the
    resulting ops dispatch asynchronously through XLA.

    Backward is a sync point for bulked dispatch: any ops still parked in
    the thread's lazy segment flush first, which also populates the
    segment's tape node (one ``jax.vjp`` over the fused forward) — only
    then is the tape complete enough to walk.
    """
    from .engine import flush_pending
    flush_pending()
    heads = list(heads)
    if head_grads is None:
        head_grads = [None] * len(heads)

    # ---- collect reachable graph + topo order ----
    visited = {}
    order: List[TapeNode] = []

    def visit(node: TapeNode):
        state = visited.get(id(node))
        if state == 2:
            return
        if state == 1:
            raise MXNetError("cycle in autograd tape")
        visited[id(node)] = 1
        for p in node.parents:
            if p is not None and p.node is not None:
                visit(p.node)
        visited[id(node)] = 2
        order.append(node)

    pending = {}  # id(node) -> list[Optional[ct]] per output

    def add_ct(node: TapeNode, idx: int, ct):
        lst = pending.setdefault(id(node), [None] * len(node.out_avals))
        lst[idx] = ct if lst[idx] is None else lst[idx] + ct

    any_graph = False
    for h, hg in zip(heads, head_grads):
        info = getattr(h, "_ag", None)
        if info is None:
            continue
        seed = (_ones_seed(tuple(h.shape), str(h.dtype))
                if hg is None else hg._read())
        if info.node is None:
            # head is itself a variable
            _accum_var(info, seed)
            any_graph = True
            continue
        visit(info.node)
        add_ct(info.node, info.index, seed)
        any_graph = True
    if not any_graph:
        raise MXNetError("this array is not connected to the recorded graph; "
                         "call backward inside/after autograd.record()")

    # ---- reverse walk ----
    for node in reversed(order):
        cts = pending.pop(id(node), None)
        if cts is None:
            continue
        full = tuple(ct if ct is not None else _zeros_ct(av)
                     for ct, av in zip(cts, node.out_avals))
        out_ct = full if node.multi_out else full[0]
        if node.runner_safe:
            in_cts = _vjp_runner()(node.vjp_fn, out_ct)
        else:
            # hand-built vjp wrappers, bare-jax.vjp fallbacks (fresh
            # treedef per call), and the already-compiled CachedOp vjp
            # run as written
            in_cts = node.vjp_fn(out_ct)
        if not retain_graph:
            node.vjp_fn = None
        for parent, ct in zip(node.parents, in_cts):
            if parent is None or _is_float0(ct) or ct is None:
                continue
            if parent.is_variable:
                _accum_var(parent, ct)
            elif parent.node is not None:
                add_ct(parent.node, parent.index, ct)


def _accum_var(info: AGInfo, ct) -> None:
    if info.grad_req == "null":
        return
    g = info.grad
    if info.grad_req == "write" and info.fresh:
        g._set_data(ct.astype(g._read().dtype) if ct.dtype != g.dtype else ct)
        info.fresh = False
    else:
        cur = g._read()
        g._set_data(cur + ct.astype(cur.dtype))


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Functional gradient: returns grads of ``heads`` w.r.t. ``variables``.

    Reference: mx.autograd.grad.  create_graph (higher-order) is supported by
    re-recording through the vjp closures is NOT yet implemented — raises.
    """
    from .ndarray import zeros
    if create_graph:
        raise MXNetError("create_graph=True not yet supported")
    heads = heads if isinstance(heads, (list, tuple)) else [heads]
    # Tape parents captured the variables' AGInfo objects at record time, so
    # redirect gradients by swapping buffers on those same infos.
    infos = []
    for v in variables:
        info = getattr(v, "_ag", None)
        if info is None:
            raise MXNetError("each variable must have attach_grad() called "
                             "before the computation was recorded")
        infos.append((info, info.grad, info.grad_req, info.fresh))
    gbufs = [zeros(v.shape, ctx=v.context, dtype=v.dtype) for v in variables]
    for (info, *_), g in zip(infos, gbufs):
        info.grad, info.grad_req, info.fresh = g, "write", True
    try:
        backward(heads, head_grads, retain_graph=bool(retain_graph),
                 train_mode=train_mode)
    finally:
        for info, g0, req0, fresh0 in infos:
            info.grad, info.grad_req, info.fresh = g0, req0, fresh0
    return gbufs


def get_symbol(x):
    """Reference parity stub: returns the traced Symbol for an output.

    The symbolic view of recorded computation lives in mxnet_tpu.symbol; the
    imperative tape here records vjp closures, not nnvm nodes, so this raises
    with guidance (use HybridBlock/hybridize or the Symbol API directly).
    """
    raise MXNetError("get_symbol is not supported on the imperative tape; "
                     "use HybridBlock.hybridize() or the Symbol API")


class Function:
    """Custom differentiable function (reference: mx.autograd.Function).

    Subclass and implement ``forward(self, *inputs)`` and
    ``backward(self, *output_grads)`` operating on NDArrays.  Internally the
    pair is registered on the tape as a single node whose vjp calls the
    user's ``backward`` under ``pause()``.
    """

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *arrays):
        self._saved = arrays

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray import NDArray, array as _mkarr
        with pause():
            outputs = self.forward(*inputs)
        multi = isinstance(outputs, (list, tuple))
        outs = list(outputs) if multi else [outputs]
        if is_recording():
            parents = [getattr(x, "_ag", None) if isinstance(x, NDArray)
                       else None for x in inputs]
            if any(p is not None for p in parents):
                fn = self

                def vjp_fn(out_ct):
                    cts = out_ct if isinstance(out_ct, tuple) else (out_ct,)
                    with pause():
                        in_grads = fn.backward(*[_mkarr(c) for c in cts])
                    if not isinstance(in_grads, (list, tuple)):
                        in_grads = [in_grads]
                    return tuple(g._read() if isinstance(g, NDArray) else g
                                 for g in in_grads)

                node = TapeNode(type(self).__name__, vjp_fn, parents,
                                [(o.shape, o.dtype) for o in outs], multi)
                for i, o in enumerate(outs):
                    o._ag = AGInfo(node=node, index=i)
        return outputs
