"""mx.AttrScope (reference: python/mxnet/attribute.py) — scoped default
attributes stamped onto every symbol created inside the ``with`` block.
The Symbol-era model-parallel idiom rides this: ``with mx.AttrScope(
ctx_group='stage1'):`` tags ops for ``bind(group2ctx=...)`` placement;
here those tags flow to the sharding rules instead of a PlaceDevice pass.
"""
from __future__ import annotations

import threading
from typing import Dict

__all__ = ["AttrScope", "current"]

_state = threading.local()


def current() -> "AttrScope":
    stack = getattr(_state, "stack", None)
    if not stack:
        _state.stack = [AttrScope()]
    return _state.stack[-1]


class AttrScope:
    _RESERVED = ("shape", "dtype", "aux", "init", "layout")

    def __init__(self, **kwargs):
        for k, v in kwargs.items():
            if not isinstance(v, str):
                raise ValueError("AttrScope values must be strings")
            if k in self._RESERVED or (k.startswith("__")
                                       and k.endswith("__")):
                # dunder-wrapping these would collide with the internal
                # metadata namespace (__shape__/__dtype__/__aux__/...)
                raise ValueError(
                    f"AttrScope key {k!r} is reserved for internal "
                    "variable metadata")
        self._attrs: Dict[str, str] = dict(kwargs)

    def get(self, attrs: Dict[str, str] = None) -> Dict[str, str]:
        """Active scope attrs (``__key__``-wrapped, so they ride node
        attrs as metadata rather than op parameters) merged under
        explicitly-passed ones."""
        out = {f"__{k}__": v for k, v in self._attrs.items()}
        if attrs:
            out.update(attrs)
        return out

    def __enter__(self):
        if not hasattr(_state, "stack"):
            _state.stack = [AttrScope()]
        merged = dict(_state.stack[-1]._attrs)
        merged.update(self._attrs)
        scope = AttrScope()
        scope._attrs = merged
        _state.stack.append(scope)
        return self

    def __exit__(self, *exc):
        _state.stack.pop()
