"""Foundations: error model, env-var config registry, dtype maps.

TPU-native rebuild of the roles played in the reference by dmlc-core
(logging/CHECK macros, `dmlc::GetEnv` env-var config — SURVEY.md §5.6) and
`python/mxnet/base.py` (error propagation, name managers).  There is no C ABI
here: the "core" is JAX/XLA, so errors are plain Python exceptions and the
config registry is a typed view over ``os.environ``.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Optional

import numpy as _np

__all__ = [
    "MXNetError",
    "is_channels_last",
    "register_env",
    "get_env",
    "list_env",
    "hot_path",
    "string_types",
    "numeric_types",
    "integer_types",
    "dtype_np",
    "dtype_name",
    "default_dtype",
]

string_types = (str,)
numeric_types = (float, int, _np.generic)
integer_types = (int, _np.integer)


class MXNetError(RuntimeError):
    """Default error type for this framework.

    Mirrors the reference's ``mxnet.base.MXNetError`` which surfaces C-side
    ``dmlc::Error``; here errors originate in Python/JAX directly.
    """


def hot_path(kind: str) -> Callable:
    """Marker decorator: this function is a hot-path ROOT for mxlint's
    interprocedural rules.  Zero runtime cost (returns the function
    unchanged, tagged); the lint reads the decoration statically.

    ``kind``:
      - ``"dispatch"`` — the per-op dispatch/flush path (engine push,
        bulk-segment defer/flush).  Code reachable from here must stay
        PURE: no allocation, env reads, lock creation, or logging
        (rule ``hot-path-purity``), and must not hide host syncs
        (rule ``hidden-host-sync``).
      - ``"step"`` — the per-step training/serving path.  Allocation is
        fine here (checkpointing etc.), but hidden host syncs
        (``.asnumpy()``/``.item()``/value casts on device arrays) still
        serialize the async engine and are flagged.
    """
    if kind not in ("dispatch", "step"):
        raise ValueError(f"hot_path kind must be 'dispatch' or 'step', "
                         f"got {kind!r}")

    def mark(fn):
        fn.__mxlint_hot_path__ = kind
        return fn
    return mark


_CHANNELS_LAST = {"NWC": 1, "NHWC": 2, "NDHWC": 3}


def is_channels_last(layout, ndim=None):
    """True for the channels-last conv/pool layouts (NWC/NHWC/NDHWC).
    With ``ndim`` given, a rank-mismatched layout string raises instead
    of being silently remapped."""
    if layout not in _CHANNELS_LAST:
        return False
    if ndim is not None and _CHANNELS_LAST[layout] != ndim:
        raise MXNetError(
            f"layout {layout!r} is for {_CHANNELS_LAST[layout]}d "
            f"convolution/pooling, got {ndim}d")
    return True


def force_cpu_mesh(n_devices: int, verify: bool = True) -> None:
    """Force jax onto a virtual ``n_devices``-device CPU mesh.

    Must run before the first jax backend query.  Two steps are required
    (this image's sitecustomize registers the axon TPU backend at
    interpreter boot and forces the platform, so ``JAX_PLATFORMS=cpu`` in
    the shell environment is ignored):

    1. ``XLA_FLAGS --xla_force_host_platform_device_count=n`` — rewritten
       in place if a different count is already present and the backend is
       not yet initialized.
    2. ``jax.config.update("jax_platforms", "cpu")`` — the counter-override
       that beats sitecustomize.

    Used by ``tests/conftest.py`` and ``__graft_entry__.dryrun_multichip``.
    """
    import re

    flag = f"--xla_force_host_platform_device_count={n_devices}"
    flags = os.environ.get("XLA_FLAGS", "")
    flags, n_sub = re.subn(
        r"--xla_force_host_platform_device_count[= ]\S+", flag, flags)
    if not n_sub:
        flags = (flags + " " + flag).strip()
    os.environ["XLA_FLAGS"] = flags

    import jax

    jax.config.update("jax_platforms", "cpu")
    if not verify:
        # caller must do something that must precede the first backend
        # query (e.g. jax.distributed.initialize) — skip the device check
        return
    devs = jax.devices()
    if devs[0].platform != "cpu":
        raise MXNetError(
            f"force_cpu_mesh: platform is {devs[0].platform!r}, not cpu — "
            "a jax backend was already initialized before this call")
    if len(devs) < n_devices:
        raise MXNetError(
            f"force_cpu_mesh: requested {n_devices} devices but only "
            f"{len(devs)} are visible — XLA_FLAGS was read before it could "
            "be rewritten (jax backend initialized too early)")


# ---------------------------------------------------------------------------
# Environment-variable config registry (reference: ~100 MXNET_* vars read via
# dmlc::GetEnv, documented in docs/faq/env_var.md — SURVEY.md §5.6).
# ---------------------------------------------------------------------------

class _EnvEntry:
    __slots__ = ("name", "default", "typ", "help")

    def __init__(self, name: str, default: Any, typ: Callable, help: str):
        self.name = name
        self.default = default
        self.typ = typ
        self.help = help


_env_registry: Dict[str, _EnvEntry] = {}
_env_lock = threading.Lock()


def _parse_bool(v: Any) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).strip().lower() in ("1", "true", "yes", "on")


def register_env(name: str, default: Any, typ: Callable = str, help: str = "") -> None:
    """Register an ``MXNET_*`` style environment variable with a typed default."""
    if typ is bool:
        typ = _parse_bool
    with _env_lock:
        _env_registry[name] = _EnvEntry(name, default, typ, help)


def get_env(name: str, default: Any = None) -> Any:
    """Read a registered env var, applying its type; unregistered names fall
    back to raw ``os.environ`` access with ``default``."""
    entry = _env_registry.get(name)
    raw = os.environ.get(name)
    if entry is None:
        return raw if raw is not None else default
    if raw is None:
        return entry.default
    try:
        return entry.typ(raw)
    except (TypeError, ValueError):
        return entry.default


def list_env() -> Dict[str, Any]:
    """All registered env vars with their current effective values."""
    return {k: get_env(k) for k in sorted(_env_registry)}


# Core knobs (subset of the reference's env_var.md; registered at import so
# `list_env()` documents them).
register_env("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice", str,
             "Engine type: NaiveEngine (sync, debug) or ThreadedEnginePerDevice (async).")
register_env("MXNET_EXEC_BULK_EXEC_TRAIN", True, bool,
             "Fuse op sequences into bulked dispatch segments (maps to jit).")
register_env("MXNET_ENGINE_BULK_SIZE", 15, int,
             "Max ops per bulked dispatch segment before a forced flush.")
register_env("MXNET_ENGINE_BULK_FUSE", "exact", str,
             "Bulk segment codegen: 'exact' (one dispatch, per-op kernels, "
             "bitwise-identical to unbulked) or 'aggressive' (full XLA "
             "fusion incl. taped segments; FMA contraction may shift "
             "results by ~1 ulp).")
register_env("MXNET_ENFORCE_DETERMINISM", False, bool,
             "Request deterministic kernel selection (XLA default is deterministic).")
register_env("MXNET_GPU_MEM_POOL_RESERVE", 5, int,
             "Percent of device memory to keep free (advisory under XLA).")
register_env("MXNET_TEST_SEED", None, int, "Seed override for the test harness.")
register_env("MXNET_SAFE_ACCUMULATION", True, bool,
             "Accumulate fp16/bf16 reductions in fp32.")
register_env("MXNET_DEFAULT_DTYPE", "float32", str,
             "Default dtype for new arrays (float32; set bfloat16 for TPU-native).")
register_env("MXNET_MATMUL_PRECISION", "", str,
             "jax matmul precision override; 'highest' forces full fp32 "
             "accumulation (reference-exact numerics, ~3x slower matmuls).")
register_env("MXNET_OPTIMIZER_AGGREGATION_SIZE", 4, int,
             "Max weights updated per fused multi-tensor optimizer call.")
register_env("MXNET_TEST_DEFAULT_CTX", "", str,
             "Context the test harness runs in, e.g. 'tpu(0)' "
             "(the import-and-rerun TPU suite sets it).")
register_env("MXNET_PALLAS_INTERPRET", False, bool,
             "Run Pallas kernels in interpret mode (CPU-testable kernels).")
register_env("MXNET_ATTENTION_KERNEL", "auto", str,
             "Attention path: 'auto' (flash when eligible), 'flash' "
             "(force the Pallas kernel), or 'xla' (full-softmax XLA path).")
register_env("MXNET_USE_FLASH_ATTENTION", "", str,
             "Legacy tri-state attention override: '1' forces flash, "
             "'0' forces XLA, unset defers to MXNET_ATTENTION_KERNEL.")
register_env("MXTPU_DIST_TIMEOUT", 300.0, float,
             "Per-attempt timeout (seconds) for joining the process group "
             "and for the coordination-service KV/barrier collectives.")
register_env("MXTPU_FAULT_PLAN", "", str,
             "Deterministic fault-injection schedule, e.g. "
             "'step_error@3;nan@5;ckpt_fail@2;loader_stall@4:1.5'.")
register_env("MXTPU_METRICS_PORT", "", str,
             "Serve the Prometheus /metrics endpoint on this port "
             "(unset = no HTTP server).")
register_env("MXTPU_METRICS_JSONL", "", str,
             "Append periodic registry snapshots to this JSONL path "
             "(unset = no writer).")
register_env("MXTPU_METRICS_INTERVAL", 60.0, float,
             "Seconds between JSONL metric snapshots.")
register_env("MXTPU_METRICS_AGGREGATE", False, bool,
             "Serve the fleet (all-hosts) view from /metrics, every "
             "series host-labeled; refreshed at checkpoint boundaries.")
register_env("MXTPU_FLIGHT_STEPS", 256, int,
             "Crash flight-recorder ring capacity in steps (0 disables).")
register_env("MXTPU_FLIGHT_PATH", "", str,
             "Crash flight-recorder dump file "
             "(default <tmpdir>/mxtpu_flight_<pid>.json).")
register_env("MXTPU_SERVING_MAX_BATCH", 8, int,
             "Serving: max requests fused into one batched CachedOp "
             "call; batch buckets are powers of two up to this.")
register_env("MXTPU_SERVING_QUEUE_DEPTH", 256, int,
             "Serving: admission-queue bound; submits beyond it are "
             "rejected with ServerOverloaded (the HTTP-429 analog).")
register_env("MXTPU_SERVING_DEADLINE_MS", 100.0, float,
             "Serving: default per-request deadline; requests still "
             "queued when it expires are rejected at batch assembly "
             "(429-style). 0 disables.")
register_env("MXTPU_SERVING_WORKERS", 2, int,
             "Serving: dispatch worker threads; >1 lets batch "
             "formation overlap device execution.")
register_env("MXTPU_SERVING_BATCH_WINDOW_US", 2000.0, float,
             "Serving: how long the batcher waits for the current "
             "shape bucket to fill before dispatching a partial batch. "
             "Read live per batch, so the BatchWindowController (and "
             "operators) can adapt it on a running server.")
register_env("MXTPU_SERVING_KV_BLOCK", 16, int,
             "Serving: KV-cache block size in token positions; the "
             "paging granularity of the generation scheduler's block "
             "manager (serving.kv_cache).")
register_env("MXTPU_SERVING_KV_BLOCKS", 128, int,
             "Serving: total KV-cache blocks pre-allocated per "
             "generation server (block 0 is reserved scratch, so "
             "usable capacity is one less).  Admission to the running "
             "batch gates on a worst-case block reservation against "
             "this pool.")
register_env("MXTPU_SERVING_DECODE_SLOTS", 4, int,
             "Serving: running-batch slot count of the iteration-level "
             "decode scheduler — how many requests decode together in "
             "one compiled decode step.  Recompile-costly; the "
             "DecodeSlotController hill-climbs it between generations.")
register_env("MXTPU_SERVING_PREFILL_MODE", "interleave", str,
             "Serving: 'interleave' admits at most one prompt prefill "
             "per decode iteration (smooth decode cadence); 'step' "
             "prefills every admissible queued request before the next "
             "decode step (fastest drain of a burst).  Read live per "
             "iteration.")
register_env("MXTPU_SERVING_MAX_NEW_TOKENS", 64, int,
             "Serving: default cap on generated tokens per request "
             "when submit_generate() is not given max_new_tokens; also "
             "bounds the worst-case KV block reservation.")
register_env("MXTPU_FRONTEND_PORT", "", str,
             "Serving: TCP port for the multi-model HTTP frontend "
             "(mxnet_tpu.serving.HttpFrontend — JSON predict, SSE "
             "token streaming, W3C traceparent).  Empty (default) "
             "binds an ephemeral port; the frontend only listens when "
             "constructed explicitly.")
register_env("MXTPU_FRONTEND_PRIORITY", 0, int,
             "Serving: default priority for models loaded into the "
             "ModelRegistry without an explicit one (higher = more "
             "important; models below the registry shed level are "
             "429'd at the door).")
register_env("MXTPU_FRONTEND_SLO_MS", 0.0, float,
             "Serving: default per-model p99 latency SLO in ms for "
             "models loaded without an explicit slo_ms — the budget "
             "the SloController defends (0 = no SLO, never watched).")
register_env("MXTPU_TUNE_SLO", True, bool,
             "Self-tuning: enable the SloController (watches each "
             "registered model's socket-to-socket request p99 against "
             "its SLO; sheds lowest-priority-first via the registry "
             "gate and scales the violator's dispatch workers).  "
             "Per-registry instance surface: attach it explicitly.")
register_env("MXTPU_TUNE_DECODE_SLOTS", False, bool,
             "Self-tuning: enable the DecodeSlotController (hill-climbs "
             "MXTPU_SERVING_DECODE_SLOTS on interval tokens/s with the "
             "bracketing stop; recompiles are the cost, so it parks at "
             "the bracketed best).  Off by default: attach it to a "
             "generation server explicitly.")
register_env("MXTPU_TUNE_INTERVAL", 2.0, float,
             "Self-tuning: seconds between controller timer-thread "
             "ticks (mxnet_tpu.tuning).")
register_env("MXTPU_TUNE_DRY_RUN", False, bool,
             "Self-tuning: compute and record every controller "
             "decision (tuning.* metrics + flight ring) but apply "
             "nothing — the observe-before-trust mode.")
register_env("MXTPU_TUNE_BULK", True, bool,
             "Self-tuning: enable the BulkSizeController "
             "(hill-climbs MXNET_ENGINE_BULK_SIZE from the live "
             "engine.flush_us histogram) when the runtime starts.")
register_env("MXTPU_TUNE_PREFETCH", True, bool,
             "Self-tuning: enable the PrefetchController (adapts the "
             "DataLoader prefetch depth from the loader.prefetch_depth "
             "gauge) when the runtime starts.")
register_env("MXTPU_TUNE_BATCH_WINDOW", True, bool,
             "Self-tuning: enable the BatchWindowController (adapts "
             "MXTPU_SERVING_BATCH_WINDOW_US from serving.queue_depth "
             "and serving.request_us p99) when the runtime starts.")
register_env("MXTPU_TUNE_FLEET_GATHER", True, bool,
             "Self-tuning: enable the FleetGatherController (streams "
             "the multi-host metric gather over the barrier-free "
             "KV-store transport on the timer thread) when the runtime "
             "starts in an initialized process group.")
register_env("MXTPU_COMPILE_CACHE_DIR", "", str,
             "Persistent compilation cache directory: exact-mode bulk "
             "segments and HybridBlock cached-graph executables are "
             "serialized here and reloaded by later processes, so a "
             "restart (auto-resume, server cold start) skips the XLA "
             "compile.  Unset disables.")
register_env("MXTPU_COMPILE_CACHE_JAX", True, bool,
             "With MXTPU_COMPILE_CACHE_DIR set, also point jax's own "
             "persistent compilation cache at <dir>/jax so plain "
             "jax.jit paths (per-op fns, training vjp graphs) reuse "
             "compiles across processes too.")
register_env("MXTPU_ELASTIC", False, bool,
             "Elastic-fleet mode for init_process_group: raises the "
             "coordination service's own task-heartbeat tolerance to "
             "effectively-forever so a dead host does NOT make the "
             "service propagate a fatal error that terminates every "
             "survivor (~100s after the death, with jax defaults).  "
             "Liveness then belongs solely to the membership lease "
             "layer (parallel.membership), which detects the loss "
             "within MXTPU_ELASTIC_LEASE_TTL and re-forms.  Leave off "
             "for non-elastic jobs, where whole-fleet fail-fast on a "
             "dead host is the desired behavior.")
register_env("MXTPU_ELASTIC_LEASE_TTL", 10.0, float,
             "Elastic-fleet membership lease TTL in seconds: a host "
             "whose heartbeat lease has not advanced for this long (on "
             "the OBSERVER's clock — no cross-host clock trust) is "
             "declared dead and the survivors re-form.  Lower = faster "
             "host-loss detection, higher = more tolerance for GC/IO "
             "pauses.")
register_env("MXTPU_ELASTIC_HEARTBEAT", 2.0, float,
             "Elastic-fleet heartbeat publish interval in seconds "
             "(should be several times smaller than "
             "MXTPU_ELASTIC_LEASE_TTL so one dropped publish never "
             "reads as a death).")
register_env("MXTPU_ELASTIC_COORD_LINGER", 8.0, float,
             "Seconds a dirty-detaching process that HOSTS the "
             "coordination service lingers before its final os._exit: "
             "the service's death severs every peer's fabric mid-RPC "
             "(jax's error polling then aborts them), so the "
             "coordinator gives peers still wrapping up — or a fenced "
             "host still discovering its exclusion — time to exit "
             "with their own clean codes first.")
register_env("MXTPU_ELASTIC_REFORM_TIMEOUT", 60.0, float,
             "Wall-clock budget in seconds for one fleet re-form round "
             "(view exchange, plan, acks, commit).  A survivor that "
             "cannot complete the round within it raises FleetLost "
             "instead of waiting forever on a fleet that cannot agree.")
register_env("MXTPU_ZERO_STAGE", 0, int,
             "Default ZeRO optimizer-state partitioning stage for "
             "ShardedTrainer (0, 1 or 2).  0 = optimizer state "
             "replicated on every chip (bitwise-identical to the "
             "pre-ZeRO step); 1 = state sharded 1/dp per chip, "
             "gradients reduce-scattered into each chip's slice and "
             "updated params all-gathered inside the one jitted step; "
             "2 = the gradient (accumulation) buffer is sharded too.  "
             "The zero_stage= constructor argument overrides.")
register_env("MXTPU_ACCUM_STEPS", 1, int,
             "Default microbatched gradient accumulation for "
             "ShardedTrainer: the step consumes its global batch as N "
             "sequential microbatches under a lax.scan (per-microbatch "
             "RNG split, rescale-correct vs the full batch), so global "
             "batch scales past per-chip activation memory.  The "
             "accum_steps= constructor argument overrides.")
register_env("MXTPU_PREEMPT_COORD", True, bool,
             "Coordinated preemption checkpoints: in a multi-process "
             "group, a SIGTERM'd ResilientTrainer publishes a flush "
             "vote over the coordination-service KV tier (no device "
             "collective) and every host commits the SAME state-<t> "
             "checkpoint — the agreed step is the max of all hosts' "
             "votes.  Off = each host flushes unilaterally at its own "
             "step (the pre-coordination behavior).")
register_env("MXTPU_PREEMPT_POLL", 0.05, float,
             "Poll interval in seconds for the preemption-coordination "
             "vote wait (bounded overall by MXTPU_DIST_TIMEOUT, after "
             "which the host falls back to a unilateral flush).")
register_env("MXTPU_COMM_BUCKET_MB", 0.0, float,
             "Bucketed gradient reduce-scatter for ShardedTrainer: "
             "split the step's gradients into buckets of at most this "
             "many MB (in reverse parameter order — the order backward "
             "materializes them) and pin each bucket's dp-reduction "
             "with an optimization_barrier-ordered sharding "
             "constraint, so XLA's latency-hiding scheduler can "
             "overlap the per-bucket collectives with the remaining "
             "backward compute.  0 (the default) = one fused "
             "reduction after the full backward — bitwise-identical "
             "to the pre-bucketing step.  The comm_bucket_mb= "
             "constructor argument overrides.")
register_env("MXTPU_DEVICE_PREFETCH", 0, int,
             "DataLoader device-input double buffering: keep up to N "
             "batches resident on device beyond the one being "
             "consumed, transferred through an async jax.device_put "
             "stage (sharding-aware when a ShardedTrainer's "
             "place_batch is attached), so step t's jit consumes an "
             "already-resident batch while t+1 transfers.  0 (the "
             "default) = off: every step pays the host->device "
             "ingestion transfer on the critical path.  The "
             "device_prefetch= constructor argument overrides; "
             "applied at each __iter__.")
register_env("MXTPU_ASYNC_CKPT", False, bool,
             "Async distributed checkpoints: the host-local npz "
             "checkpoint write (the multi-process fleet path) "
             "snapshots state at the step boundary and commits on a "
             "background thread, and the coordinated-preemption KV "
             "vote wait moves off the step path (hosts keep stepping "
             "toward the highest vote seen while the round resolves). "
             "Committed-dir semantics are unchanged: a crash mid-"
             "write leaves a torn tmp dir that resume filters out.  "
             "Off (the default) = the blocking PR-10 flush.")
register_env("MXTPU_SPARSE_GRAD", True, bool,
             "Row-sparse embedding gradients inside the sharded step: "
             "an Embedding(sparse_grad=True) produces its gradient as "
             "(values, unique_ids) via an in-graph segment-sum over "
             "the batch's deduplicated ids, and SGD/Adam lazy updates "
             "gather/update/scatter only the live rows — per-step "
             "update cost scales with batch-unique ids, not vocab.  "
             "Off = such embeddings fall back to dense gradients "
             "(bitwise the pre-sparse step).")
register_env("MXTPU_SPARSE_ID_BUCKET", 0, int,
             "Fixed id-bucket capacity for the sparse embedding "
             "gradient path (rounded up to a power of 2).  0 (the "
             "default) sizes the bucket per compiled batch shape: the "
             "next power of 2 >= the batch's id count.  Setting it "
             "larger pins ONE bucket size across varying batch "
             "shapes (one compiled step); a value smaller than a "
             "batch's id count is clamped up to that batch's own "
             "bucket — capacity below the id count could drop rows.")
register_env("MXTPU_SPARSE_EXCHANGE", True, bool,
             "Coalesced cross-worker exchange for row-sparse "
             "gradients in the gluon Trainer: workers allgather "
             "(ids, rows) pairs over dist.allgather_rows and "
             "dedup+sum on the host (the modern ps-lite push/pull) "
             "instead of allreducing the dense matrix.  Off = sparse "
             "grads densify before the wire.")
register_env("MXTPU_TUNE_COMM_BUCKET", True, bool,
             "Self-tuning: enable the CommBucketController (hill-"
             "climbs a ShardedTrainer's MXTPU_COMM_BUCKET_MB on the "
             "resilience.step_us interval mean) when one is "
             "constructed with a trainer.  Not in the stock runtime "
             "set — it needs a live trainer reference.")
register_env("MXTPU_TRACE", False, bool,
             "Causal tracing: record request/step span trees with "
             "W3C-style trace/span ids (observability.tracing), "
             "propagate contexts through serving batches, training "
             "steps, and the coordination-service KV tier, and attach "
             "trace-id exemplars to every histogram bucket.  Off (the "
             "default) = the instrumented paths pay one memoized env "
             "probe and nothing else.")
register_env("MXTPU_TRACE_SAMPLE", 1, int,
             "Causal tracing head sampling: start a new ROOT trace for "
             "1 in N sampling decisions (1 = trace every root; "
             "children of a sampled trace are always recorded, so "
             "traces stay whole).  Fleet-lockstep roots (training "
             "steps) sample deterministically on the step index, so "
             "every host keeps or drops the same step.")
register_env("MXTPU_TRACE_RING", 2048, int,
             "Causal tracing: bounded ring capacity of completed spans "
             "kept in memory for exemplar resolution, chrome-trace "
             "export, and crash dumps (resolved when tracing first "
             "switches on).")
register_env("MXTPU_TRACE_JSONL", "", str,
             "Causal tracing: append completed spans to this JSONL "
             "path (size-rotated, buffered ~64 spans per write; one "
             "file per host — concatenate hosts' files and feed "
             "tracing.chrome_trace_from_spans for a cross-host "
             "timeline).  Unset disables the stream; the in-memory "
             "ring always records.")
register_env("MXTPU_TUNE_DEVICE_PREFETCH", True, bool,
             "Self-tuning: enable the DevicePrefetchController "
             "(adapts the DataLoader device-prefetch depth from the "
             "loader.device_buffer_depth gauge — each slot is a "
             "resident device batch, i.e. HBM) when the runtime "
             "starts.")
register_env("MXTPU_PROF_SAMPLE_HZ", 0.0, float,
             "Continuous stack-sampling profiler: walk every thread's "
             "frames (sys._current_frames) this many times per second, "
             "folding them into collapsed-stack (flamegraph) counts in "
             "rotating profile windows.  0 (the default) = off; the "
             "off path on instrumented start sites is one memoized "
             "env probe.")
register_env("MXTPU_PROF_WINDOW_SECS", 60.0, float,
             "Stack sampler: seconds of samples per profile window "
             "before it rotates into the bounded window ring "
             "(/debug/profile and watchdog postmortems serve the "
             "current + recent windows).")
register_env("MXTPU_PROF_WINDOWS", 8, int,
             "Stack sampler: how many rotated profile windows to keep "
             "(a bounded ring — memory is bounded by windows x "
             "distinct folded stacks per window).")
register_env("MXTPU_DEBUG_ENDPOINTS", False, bool,
             "Serve the live-introspection /debug/* surface "
             "(/debug/stacks, /debug/profile, /debug/flight, "
             "/debug/trace/<id>, /debug/vars) from the serving "
             "HttpFrontend and the MXTPU_METRICS_PORT exporter.  Off "
             "(the default) = those paths 404; the endpoints are "
             "auth-free, so only enable them on trusted networks.")
register_env("MXTPU_WATCHDOG_FACTOR", 0.0, float,
             "Progress watchdog: flag a heartbeat touchpoint (trainer "
             "step, decode loop, dispatch workers) as stalled when it "
             "goes silent for FACTOR x its own recent p99 interval "
             "(from the metrics spine), then dump one postmortem "
             "bundle (stacks + flight rings + span ring + profile "
             "window).  0 (the default) = off; typical values 4-10.")
register_env("MXTPU_WATCHDOG_ACTION", "dump", str,
             "Progress watchdog action on a detected stall: 'dump' "
             "(write the postmortem bundle and keep running) or "
             "'term' (dump, then SIGTERM the process so the existing "
             "drain/checkpoint handlers take over).")
register_env("MXTPU_STACKS_SIGNAL", "SIGQUIT", str,
             "Signal that dumps all-thread stacks + flight rings to "
             "the flight path WITHOUT killing the process (the manual "
             "'what is it doing right now' probe; chains any previous "
             "handler).  Named signal (SIGQUIT, SIGUSR2, ...); empty "
             "disables installation.")


# ---------------------------------------------------------------------------
# Dtypes
# ---------------------------------------------------------------------------

_DTYPE_ALIASES: Dict[str, str] = {
    "float32": "float32", "float64": "float64", "float16": "float16",
    "bfloat16": "bfloat16", "uint8": "uint8", "int8": "int8",
    "int32": "int32", "int64": "int64", "int16": "int16", "uint16": "uint16",
    "uint32": "uint32", "uint64": "uint64", "bool": "bool",
}


def dtype_np(dtype: Any) -> "_np.dtype":
    """Canonicalize a dtype spec (str / np.dtype / jnp dtype) to np.dtype.

    bfloat16 round-trips via ml_dtypes (numpy has no native bfloat16).
    """
    if dtype is None:
        return _np.dtype(default_dtype())
    if isinstance(dtype, str):
        name = _DTYPE_ALIASES.get(dtype)
        if name is None:
            raise MXNetError(f"unknown dtype {dtype!r}")
        if name == "bfloat16":
            import ml_dtypes
            return _np.dtype(ml_dtypes.bfloat16)
        return _np.dtype(name)
    return _np.dtype(dtype)


def jax_compute_dtype(dtype: Any) -> "_np.dtype":
    """The dtype jax will actually store: under the int32 default
    (``runtime.enable_large_tensor()`` off), 64-bit requests map to their
    32-bit duals — the DOCUMENTED large-tensor truncation contract
    (runtime.py), applied explicitly here so jax never emits its
    truncation UserWarning on the library's own paths."""
    d = dtype_np(dtype)
    import jax
    if not jax.config.jax_enable_x64 and d.itemsize == 8 \
            and d.kind in "iuf":
        return _np.dtype({"i": _np.int32, "u": _np.uint32,
                          "f": _np.float32}[d.kind])
    return d


def dtype_name(dtype: Any) -> str:
    """Canonical string name for a dtype."""
    d = _np.dtype(dtype) if not isinstance(dtype, str) else dtype_np(dtype)
    return str(d.name) if d.name != "bfloat16" else "bfloat16"


def default_dtype() -> str:
    return get_env("MXNET_DEFAULT_DTYPE")


def resolve_reshape_spec(in_dims, spec, reverse=False):
    """Resolve MXNet reshape specials (src/operator/tensor/matrix_op-inl.h):
    0 = copy input dim, -1 = infer, -2 = copy all remaining dims,
    -3 = merge next two dims, -4 d1 d2 = split one dim into (d1, d2).
    ``reverse=True`` applies the rules right-to-left.  The single source of
    truth for both the reshape op and the NDArray.reshape view path."""
    in_dims = list(in_dims)
    spec = [int(s) for s in spec]
    # group multi-token units so reverse mode can't split a -4 triple
    units = []
    j = 0
    while j < len(spec):
        if spec[j] == -4:
            units.append(spec[j:j + 3])
            j += 3
        else:
            units.append([spec[j]])
            j += 1
    if reverse:
        # mirror both sides; a -4's operands swap roles in the mirror
        units = [([-4, u[2], u[1]] if u[0] == -4 else u)
                 for u in units[::-1]]
        in_dims = in_dims[::-1]
    out = []
    i = 0
    for u in units:
        s = u[0]
        if s == 0:
            out.append(in_dims[i])
            i += 1
        elif s == -2:
            out.extend(in_dims[i:])
            i = len(in_dims)
        elif s == -3:
            out.append(in_dims[i] * in_dims[i + 1])
            i += 2
        elif s == -4:
            d1, d2 = u[1], u[2]
            cur = in_dims[i]
            if d1 == -1:
                d1 = cur // d2
            if d2 == -1:
                d2 = cur // d1
            out.extend([d1, d2])
            i += 1
        elif s == -1:
            out.append(-1)
            i += 1
        else:
            out.append(s)
            i += 1
    if reverse:
        out = out[::-1]
    if -1 in out:
        known = 1
        for s in out:
            if s != -1:
                known *= s
        total = 1
        for s in in_dims:
            total *= s
        out[out.index(-1)] = total // max(known, 1)
    return tuple(out)


def rnn_packed_param_count(mode: str, input_size: int, hidden: int,
                           num_layers: int, bidirectional: bool) -> int:
    """Length of the packed cuDNN-layout RNN parameter vector (shared by
    symbol shape inference and mx.rnn.FusedRNNCell so the two can never
    disagree): per layer, per direction: Wx, Wh, bx, bh."""
    ngates = {"lstm": 4, "gru": 3, "rnn_tanh": 1, "rnn_relu": 1}[mode]
    ndir = 2 if bidirectional else 1
    total = 0
    layer_in = input_size
    for _ in range(num_layers):
        total += ndir * (ngates * hidden * layer_in
                         + ngates * hidden * hidden + 2 * ngates * hidden)
        layer_in = hidden * ndir
    return total
