"""Device/context model: ``mx.cpu()``, ``mx.gpu(i)``, ``mx.tpu(i)``.

Reference role: ``Context{dev_type, dev_id}`` in include/mxnet/base.h —
every NDArray and op execution is bound to a Context (SURVEY.md §2.1).
TPU-native design: a Context is a symbolic device name resolved lazily to a
``jax.Device``.  ``mx.tpu(i)`` is first-class; ``mx.gpu(i)`` resolves to the
i-th accelerator so reference scripts run unmodified on a TPU host; ``mx.cpu()``
resolves to a CPU device when the CPU platform is available, else the default
platform (XLA owns placement, unlike the reference's explicit per-device
streams).
"""
from __future__ import annotations

import threading
from typing import List, Optional

from .base import MXNetError

__all__ = ["Context", "cpu", "gpu", "tpu", "cpu_pinned", "num_gpus", "num_tpus",
           "current_context"]


def _jax():
    import jax
    return jax


class Context:
    """A symbolic device. Comparable/hashable; resolves to a jax.Device lazily."""

    # Mirrors the reference's devtype enum, extended with tpu.
    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "cpu_shared", 6: "tpu"}
    devstr2type = {v: k for k, v in devtype2str.items()}

    _default_ctx = threading.local()

    def __init__(self, device_type: str, device_id: int = 0):
        if isinstance(device_type, Context):
            device_type, device_id = device_type.device_type, device_type.device_id
        if device_type not in self.devstr2type:
            raise MXNetError(f"unknown device type {device_type!r}")
        self.device_type = device_type
        self.device_id = int(device_id)

    @classmethod
    def from_str(cls, s: str) -> "Context":
        """Parse 'cpu(0)' / 'tpu(1)' / 'cpu' (the reference's repr form)."""
        s = str(s).strip()
        kind, _, idx = s.partition("(")
        idx = idx.rstrip(")").strip()
        return cls(kind.strip(), int(idx) if idx else 0)

    # -- resolution --------------------------------------------------------
    @property
    def device(self):
        """Resolve to a concrete jax.Device."""
        return _resolve_device(self.device_type, self.device_id)

    @property
    def device_typeid(self) -> int:
        return self.devstr2type[self.device_type]

    # -- protocol ----------------------------------------------------------
    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    __str__ = __repr__

    def __enter__(self):
        if not hasattr(Context._default_ctx, "stack"):
            Context._default_ctx.stack = []
        Context._default_ctx.stack.append(self)
        return self

    def __exit__(self, *a):
        Context._default_ctx.stack.pop()

    @classmethod
    def default_ctx(cls) -> "Context":
        stack = getattr(cls._default_ctx, "stack", None)
        if stack:
            return stack[-1]
        return _default_device_context()


_ACCEL_PLATFORMS = ("tpu", "axon", "gpu", "cuda", "rocm")


def _platform_devices(kinds) -> List:
    # process-LOCAL devices: under multi-process JAX (dist_sync), a Context
    # must never resolve to another process's device — an array placed
    # there would be non-addressable here
    jax = _jax()
    for kind in kinds:
        try:
            devs = jax.local_devices(backend=kind)
            if devs:
                return devs
        except RuntimeError:
            continue
    return []


def _resolve_device(device_type: str, device_id: int):
    jax = _jax()
    if device_type in ("cpu", "cpu_pinned", "cpu_shared"):
        devs = _platform_devices(("cpu",))
        if not devs:
            devs = jax.local_devices()  # accelerator build: CPU ctx
            # falls through to the default platform; XLA handles host staging.
    elif device_type == "tpu":
        devs = _platform_devices(("tpu", "axon")) or jax.local_devices()
    else:  # gpu == "the accelerator" so reference scripts run unchanged
        devs = _platform_devices(_ACCEL_PLATFORMS) or jax.local_devices()
    if not devs:
        raise MXNetError(f"no devices for context {device_type}({device_id})")
    return devs[device_id % len(devs)]


def _default_device_context() -> Context:
    return Context("cpu", 0)


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def gpu(device_id: int = 0) -> Context:
    return Context("gpu", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def num_gpus() -> int:
    return len(_platform_devices(_ACCEL_PLATFORMS))


def num_tpus() -> int:
    return len(_platform_devices(("tpu", "axon")))


def current_context() -> Context:
    return Context.default_ctx()
