"""``mxnet_tpu.numpy_extension`` (mx.npx): operators beyond the numpy
standard, surfaced for numpy-frontend code.

Reference parity: python/mxnet/numpy_extension/ — the companion
namespace holding the DEEP-LEARNING ops (softmax, activations, the NN
layer ops, sampling) that `mx.np` deliberately keeps out of the
numpy-named surface.  Everything here IS the registry frontend under
its registry name; this module is the reference's naming convention,
not a second implementation.

``set_np()`` / ``reset_np()`` / ``is_np_array()`` mirror the reference
switches.  They gate nothing here — the two frontends coexist without a
global mode because arrays are one type — but numpy-interface code
written against the reference calls them, so they are accepted and
tracked.
"""
from __future__ import annotations

import threading as _threading

from .. import ndarray as _nd

__all__ = ["set_np", "reset_np", "is_np_array", "softmax",
           "log_softmax", "masked_softmax", "relu", "sigmoid",
           "gelu", "leaky_relu", "activation", "batch_norm",
           "layer_norm", "fully_connected", "convolution", "pooling",
           "dropout", "embedding", "topk", "pick", "one_hot",
           "gamma", "erf", "erfinv", "seed"]

_state = _threading.local()


def set_np(shape=True, array=True, dtype=False):
    """Accepted for reference compatibility (numpy semantics are always
    on for mx.np arrays here; there is no global array-type switch)."""
    _state.np_array = bool(array)
    _state.np_shape = bool(shape)
    _state.np_dtype = bool(dtype)


def reset_np():
    set_np(False, False, False)


def is_np_array() -> bool:
    return getattr(_state, "np_array", False)


# -- deep-learning ops under their reference npx names ----------------------

softmax = _nd.softmax
log_softmax = _nd.log_softmax
masked_softmax = _nd.masked_softmax
relu = _nd.relu
sigmoid = _nd.sigmoid
erf = _nd.erf
erfinv = _nd.erfinv
gamma = _nd.gamma
topk = _nd.topk
pick = _nd.pick
one_hot = _nd.one_hot
activation = _nd.Activation
batch_norm = _nd.BatchNorm
layer_norm = _nd.LayerNorm
fully_connected = _nd.FullyConnected
convolution = _nd.Convolution
pooling = _nd.Pooling
dropout = _nd.Dropout
embedding = _nd.Embedding
leaky_relu = _nd.LeakyReLU


def gelu(data):
    return _nd.gelu(data)


def seed(s):
    from .. import random as _r
    _r.seed(s)
