"""Weight initializers.

Reference parity: python/mxnet/initializer.py — the registry (`mx.init.*`),
Xavier/MSRAPrelu magnitude conventions, pattern-based dispatch by parameter
name (arrays named ``*_bias`` get zeros, etc.) as used by ParameterDict.
Draws come from the global key stream in mxnet_tpu.random.
"""
from __future__ import annotations

import math
import re
from typing import Optional

import numpy as _np

from .base import MXNetError

__all__ = ["Initializer", "Uniform", "Normal", "Zero", "One", "Constant",
           "Xavier", "MSRAPrelu", "Orthogonal", "Bilinear", "LSTMBias",
           "Mixed", "register", "create"]

_registry = {}


def register(klass):
    _registry[klass.__name__.lower()] = klass
    return klass


def create(init, **kwargs) -> "Initializer":
    if init is None:
        return Uniform(0.07)
    if isinstance(init, Initializer):
        return init
    if isinstance(init, str):
        name = init.lower()
        if name not in _registry:
            raise MXNetError(f"unknown initializer {init!r}")
        return _registry[name](**kwargs)
    raise MXNetError(f"cannot create initializer from {init!r}")


class Initializer:
    """Base initializer; dispatches by parameter name like the reference
    (``_weight``/``_bias``/``_gamma``/``_beta``/``_mean``/``_var``)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, name, arr):
        self.init_weight_by_name(name, arr)

    def init_weight(self, name, arr):
        """Direct application, bypassing name-suffix dispatch — used when a
        parameter carries an explicit initializer (reference: InitDesc with
        attrs['__init__'] skips the pattern rules)."""
        try:
            self._init_weight(name, arr)
        except NotImplementedError:
            self(name, arr)

    def init_weight_by_name(self, name: str, arr) -> None:
        name = name.lower()
        if name.endswith("bias"):
            self._init_zero(arr)
        elif name.endswith("gamma"):
            self._init_one(arr)
        elif name.endswith("beta"):
            self._init_zero(arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean"):
            self._init_zero(arr)
        elif name.endswith("running_var") or name.endswith("moving_var"):
            self._init_one(arr)
        else:
            self._init_weight(name, arr)

    # -- primitive fills ---------------------------------------------------
    def _init_zero(self, arr):
        arr[:] = _np.zeros(arr.shape, dtype=_np.float32)

    def _init_one(self, arr):
        arr[:] = _np.ones(arr.shape, dtype=_np.float32)

    def _init_weight(self, name, arr):
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}({self._kwargs})"


def _rand_uniform(shape, scale):
    from . import random as _grandom
    import jax.random as jr
    return jr.uniform(_grandom.next_key(), shape, _np.float32,
                      -scale, scale)


def _rand_normal(shape, sigma):
    from . import random as _grandom
    import jax.random as jr
    return jr.normal(_grandom.next_key(), shape, _np.float32) * sigma


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, arr):
        arr[:] = _np.asarray(_rand_uniform(arr.shape, self.scale))


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, arr):
        arr[:] = _np.asarray(_rand_normal(arr.shape, self.sigma))


@register
class Zero(Initializer):
    def _init_weight(self, name, arr):
        self._init_zero(arr)


register(Zero)
_registry["zeros"] = Zero


@register
class One(Initializer):
    def _init_weight(self, name, arr):
        self._init_one(arr)


_registry["ones"] = One


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, arr):
        arr[:] = _np.full(arr.shape, self.value, dtype=_np.float32)


def _fan(shape):
    """(fan_in, fan_out) with conv receptive-field scaling, as the
    reference's Xavier computes them."""
    hw = 1
    for s in shape[2:]:
        hw *= s
    fan_out = shape[0] * hw
    fan_in = (shape[1] if len(shape) > 1 else shape[0]) * hw
    return fan_in, fan_out


@register
class Xavier(Initializer):
    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        fan_in, fan_out = _fan(arr.shape)
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise MXNetError(f"bad factor_type {self.factor_type}")
        scale = math.sqrt(self.magnitude / max(factor, 1.0))
        if self.rnd_type == "uniform":
            arr[:] = _np.asarray(_rand_uniform(arr.shape, scale))
        else:
            arr[:] = _np.asarray(_rand_normal(arr.shape, scale))


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, name, arr):
        nout = arr.shape[0]
        nin = int(_np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = _np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = _np.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = _np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        arr[:] = (self.scale * q).reshape(arr.shape).astype(_np.float32)


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel (reference: used by UpSampling deconv)."""

    def _init_weight(self, name, arr):
        weight = _np.zeros(arr.shape, dtype=_np.float32)
        shape = arr.shape
        f = _np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(_np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight.flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight


@register
class LSTMBias(Initializer):
    """Forget-gate bias = forget_bias, others 0 (reference convention)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = _np.zeros(arr.shape, dtype=_np.float32)
        n = b.shape[0] // 4
        b[n:2 * n] = self.forget_bias
        arr[:] = b


class Mixed:
    """Pattern→initializer dispatch (reference: mx.init.Mixed)."""

    def __init__(self, patterns, initializers):
        if len(patterns) != len(initializers):
            raise MXNetError("patterns and initializers must pair up")
        self.map = [(re.compile(p), i) for p, i in zip(patterns, initializers)]

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise MXNetError(f"no initializer pattern matches {name!r}")
