"""Weight initializers.

Reference parity: python/mxnet/initializer.py — the registry (`mx.init.*`),
Xavier/MSRAPrelu magnitude conventions, pattern-based dispatch by parameter
name (arrays named ``*_bias`` get zeros, etc.) as used by ParameterDict.
Draws come from the global key stream in mxnet_tpu.random.
"""
from __future__ import annotations

import math
import re
from typing import Optional

import numpy as _np

from .base import MXNetError

__all__ = ["Initializer", "InitDesc", "Uniform", "Normal", "Zero", "One",
           "Constant", "Xavier", "MSRAPrelu", "Orthogonal", "Bilinear",
           "LSTMBias", "Mixed", "Load", "register", "create"]

_registry = {}


def register(klass):
    _registry[klass.__name__.lower()] = klass
    return klass


def create(init, **kwargs) -> "Initializer":
    if init is None:
        return Uniform(0.07)
    if isinstance(init, Initializer):
        return init
    if isinstance(init, str):
        name = init.lower()
        if name not in _registry:
            raise MXNetError(f"unknown initializer {init!r}")
        return _registry[name](**kwargs)
    if isinstance(init, type):
        # a CLASS (missing parens: initialize(mx.init.Xavier)) would be
        # silently "callable" and leave params at zero — reject loudly
        raise MXNetError(
            f"cannot create initializer from the class {init!r}; "
            f"pass an INSTANCE (e.g. {getattr(init, '__name__', init)}())")
    if callable(init):
        # Mixed/Load and user functions follow the reference's
        # (name, arr) calling convention without subclassing Initializer;
        # the adapter supplies the init_weight() surface the per-param
        # explicit-initializer call site uses
        return _CallableInit(init)
    raise MXNetError(f"cannot create initializer from {init!r}")


class Initializer:
    """Base initializer; dispatches by parameter name like the reference
    (``_weight``/``_bias``/``_gamma``/``_beta``/``_mean``/``_var``)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, name, arr):
        if isinstance(name, InitDesc):
            # reference semantics: attrs['__init__'] overrides the
            # pattern rules ("zeros" or the json '["zeros", {}]' form)
            desc = name.attrs.get("__init__")
            if desc:
                import json as _json
                try:
                    parsed = _json.loads(desc)
                except (ValueError, TypeError):
                    parsed = desc
                if isinstance(parsed, (list, tuple)):
                    sub = create(parsed[0],
                                 **(parsed[1] if len(parsed) > 1 else {}))
                else:
                    sub = create(parsed)
                sub.init_weight(str(name), arr)
                return
        self.init_weight_by_name(name, arr)

    def init_weight(self, name, arr):
        """Direct application, bypassing name-suffix dispatch — used when a
        parameter carries an explicit initializer (reference: InitDesc with
        attrs['__init__'] skips the pattern rules)."""
        try:
            self._init_weight(name, arr)
        except NotImplementedError:
            self(name, arr)

    def init_weight_by_name(self, name: str, arr) -> None:
        name = name.lower()
        if name.endswith("bias"):
            self._init_zero(arr)
        elif name.endswith("gamma"):
            self._init_one(arr)
        elif name.endswith("beta"):
            self._init_zero(arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean"):
            self._init_zero(arr)
        elif name.endswith("running_var") or name.endswith("moving_var"):
            self._init_one(arr)
        else:
            self._init_weight(name, arr)

    # -- primitive fills ---------------------------------------------------
    def _init_zero(self, arr):
        arr[:] = _np.zeros(arr.shape, dtype=_np.float32)

    def _init_one(self, arr):
        arr[:] = _np.ones(arr.shape, dtype=_np.float32)

    def _init_weight(self, name, arr):
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}({self._kwargs})"


def _rand_uniform(shape, scale):
    from . import random as _grandom
    import jax.random as jr
    return jr.uniform(_grandom.next_key(), shape, _np.float32,
                      -scale, scale)


def _rand_normal(shape, sigma):
    from . import random as _grandom
    import jax.random as jr
    return jr.normal(_grandom.next_key(), shape, _np.float32) * sigma


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, arr):
        arr[:] = _np.asarray(_rand_uniform(arr.shape, self.scale))


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, arr):
        arr[:] = _np.asarray(_rand_normal(arr.shape, self.sigma))


@register
class Zero(Initializer):
    def _init_weight(self, name, arr):
        self._init_zero(arr)


register(Zero)
_registry["zeros"] = Zero


@register
class One(Initializer):
    def _init_weight(self, name, arr):
        self._init_one(arr)


_registry["ones"] = One


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, arr):
        arr[:] = _np.full(arr.shape, self.value, dtype=_np.float32)


def _fan(shape):
    """(fan_in, fan_out) with conv receptive-field scaling, as the
    reference's Xavier computes them."""
    hw = 1
    for s in shape[2:]:
        hw *= s
    fan_out = shape[0] * hw
    fan_in = (shape[1] if len(shape) > 1 else shape[0]) * hw
    return fan_in, fan_out


@register
class Xavier(Initializer):
    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        fan_in, fan_out = _fan(arr.shape)
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise MXNetError(f"bad factor_type {self.factor_type}")
        scale = math.sqrt(self.magnitude / max(factor, 1.0))
        if self.rnd_type == "uniform":
            arr[:] = _np.asarray(_rand_uniform(arr.shape, scale))
        else:
            arr[:] = _np.asarray(_rand_normal(arr.shape, scale))


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, name, arr):
        nout = arr.shape[0]
        nin = int(_np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = _np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = _np.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = _np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        arr[:] = (self.scale * q).reshape(arr.shape).astype(_np.float32)


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel (reference: used by UpSampling deconv)."""

    def _init_weight(self, name, arr):
        weight = _np.zeros(arr.shape, dtype=_np.float32)
        shape = arr.shape
        f = _np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(_np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight.flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight


@register
class LSTMBias(Initializer):
    """Forget-gate bias = forget_bias, others 0 (reference convention)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = _np.zeros(arr.shape, dtype=_np.float32)
        n = b.shape[0] // 4
        b[n:2 * n] = self.forget_bias
        arr[:] = b


class Mixed:
    """Pattern→initializer dispatch (reference: mx.init.Mixed)."""

    def __init__(self, patterns, initializers):
        if len(patterns) != len(initializers):
            raise MXNetError("patterns and initializers must pair up")
        self.map = [(re.compile(p), i) for p, i in zip(patterns, initializers)]

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise MXNetError(f"no initializer pattern matches {name!r}")


class _CallableInit(Initializer):
    """Adapter giving bare callables (Mixed, Load, user functions) the
    Initializer surface — both the global path (__call__) and the
    explicit per-parameter path (init_weight) route to the callable."""

    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def __call__(self, name, arr):
        self._fn(name, arr)

    def init_weight(self, name, arr):
        self._fn(name, arr)


class InitDesc(str):
    """Name descriptor carrying variable attrs to the initializer
    (reference mx.init.InitDesc: a str subclass, so name-pattern
    dispatch keeps working while attrs/global_init ride along)."""

    def __new__(cls, name, attrs=None, global_init=None):
        obj = super().__new__(cls, name)
        obj.attrs = attrs or {}
        obj.global_init = global_init
        return obj


class Load:
    """Initialize from saved parameters with a fallback initializer
    (reference mx.init.Load): param is a dict name->NDArray or a file
    saved by mx.nd.save; names may carry 'arg:'/'aux:' prefixes."""

    def __init__(self, param, default_init=None, verbose=False):
        if isinstance(param, str):
            from .ndarray import load as nd_load
            param = nd_load(param)
        if not hasattr(param, "items"):
            raise MXNetError(
                "Load expects a name->NDArray dict (or a file saved from "
                "one); got a list — save params as a dict")
        self.param = {}
        for name, arr in param.items():
            if name.startswith(("arg:", "aux:")):
                name = name[4:]
            self.param[name] = arr
        # normalize eagerly: catches the missing-parens/class and
        # registry-string forms with create()'s loud errors up front
        self.default_init = None if default_init is None \
            else create(default_init)
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            src = self.param[name]
            if tuple(src.shape) != tuple(arr.shape):
                raise MXNetError(
                    f"Parameter {name!r} cannot be initialized from "
                    f"loading: incompatible shapes {tuple(src.shape)} vs "
                    f"{tuple(arr.shape)}")
            arr[:] = src
            if self.verbose:
                import logging
                logging.info("Initialized %s by loading", name)
            return
        if self.default_init is None:
            raise MXNetError(
                f"Cannot Initialize parameter {name!r}: not found in the "
                f"loaded file and no default_init given")
        self.default_init(name, arr)
        if self.verbose:
            import logging
            logging.info("Initialized %s by default", name)
