"""Flash attention as a Pallas TPU kernel.

Reference context: the reference's attention rides cuDNN/hand-CUDA
softmax(QKᵀ)V with the full (Lq, Lk) score matrix in HBM; the TPU-native
answer is the tiled online-softmax formulation (Flash Attention), which
never materializes the score matrix: each grid step owns one
(BLOCK_Q, D) query tile in VMEM and streams K/V tiles through the MXU,
carrying the running max/denominator.  HBM traffic drops from
O(Lq·Lk) to O(Lq·D + Lk·D) — exactly the memory-bound regime SURVEY §6
flags for long sequences (ring attention in parallel/ring.py handles the
multi-chip axis; this kernel is the single-chip inner loop).

Grid: (batch·heads, Lq/BLOCK_Q); the K/V sweep is a lax.fori_loop inside
the kernel over VMEM-resident K/V (one head's K/V must fit VMEM — fine
through Lk·D ≈ 512k fp32 elements; beyond that, shard Lk over the ring).

Numerics: f32 accumulation regardless of input dtype; causal masking and
right-padding masks derive from 2-D broadcasted_iota (TPU requires ≥2-D
iota).  Interpret mode runs the same kernel on CPU (tests/conftest mesh);
Mosaic compiles it on the chip (tests/test_kernels_tpu.py).
"""
from __future__ import annotations

import functools

BLOCK_Q = 128
BLOCK_K = 128
_NEG_INF = -1e30


def _interpret(example=None) -> bool:
    from .multi_sgd import _interpret as _i
    return _i(example)


@functools.lru_cache(maxsize=None)
def _build_call(bh: int, lq: int, lk: int, d: int, valid_lq: int,
                valid_lk: int, causal: bool, scale: float,
                dtype_name: str, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    nq = lq // BLOCK_Q
    nk = lk // BLOCK_K
    dtype = jnp.dtype(dtype_name)

    def kernel(q_ref, k_ref, v_ref, vl_ref, o_ref):
        qi = pl.program_id(1)
        q = q_ref[0].astype(jnp.float32) * scale          # (BQ, D)
        # per-sequence valid key length (padding mask support): the tile
        # padding bound `valid_lk` is static; vl tightens it per row
        vl = jnp.minimum(vl_ref[0], jnp.float32(valid_lk))

        def body(ki, carry):
            m, l, acc = carry
            k_blk = k_ref[0, pl.dslice(ki * BLOCK_K, BLOCK_K)].astype(
                jnp.float32)                               # (BK, D)
            v_blk = v_ref[0, pl.dslice(ki * BLOCK_K, BLOCK_K)].astype(
                jnp.float32)
            s = jax.lax.dot_general(
                q, k_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)        # (BQ, BK)
            # mask K padding (and the causal upper triangle)
            k_idx = ki * BLOCK_K + lax.broadcasted_iota(
                jnp.int32, (BLOCK_Q, BLOCK_K), 1)
            kmask = k_idx.astype(jnp.float32) < vl
            mask = kmask
            if causal:
                # bottom-right alignment (the flash/decode convention and
                # this repo's reference): query i sits at absolute key
                # position (valid_lk - valid_lq + i), so Lq=1 against a
                # length-N cache attends ALL N keys
                q_idx = qi * BLOCK_Q + lax.broadcasted_iota(
                    jnp.int32, (BLOCK_Q, BLOCK_K), 0)
                mask = mask & (k_idx <= q_idx + (valid_lk - valid_lq))
            s = jnp.where(mask, s, _NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=1))
            p = jnp.exp(s - m_new[:, None])
            # rows whose every key is masked (causal bound < 0): the
            # reference softmaxes a uniform -NEG_INF row, i.e. uniform
            # attention over the valid keys — exp(0)=1 here would
            # instead spread over PADDED slots, so substitute the valid
            # mask as the weights (masks are prefixes, so a row dead in
            # this block is dead in every block)
            dead = m_new <= (_NEG_INF * 0.5)
            p = jnp.where(dead[:, None], kmask.astype(jnp.float32), p)
            corr = jnp.exp(m - m_new)
            l_new = l * corr + jnp.sum(p, axis=1)
            acc_new = acc * corr[:, None] + jax.lax.dot_general(
                p, v_blk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return m_new, l_new, acc_new

        m0 = jnp.full((BLOCK_Q,), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((BLOCK_Q,), jnp.float32)
        a0 = jnp.zeros((BLOCK_Q, d), jnp.float32)
        m, l, acc = lax.fori_loop(0, nk, body, (m0, l0, a0))
        # rows with no valid keys (padded queries) divide by 1 instead
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc / l[:, None]).astype(dtype)

    q_spec = pl.BlockSpec((1, BLOCK_Q, d), lambda b, i: (b, i, 0))
    kv_spec = pl.BlockSpec((1, lk, d), lambda b, i: (b, 0, 0))
    vl_spec = pl.BlockSpec((1,), lambda b, i: (b,))
    return pl.pallas_call(
        kernel,
        grid=(bh, nq),
        in_specs=[q_spec, kv_spec, kv_spec, vl_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((bh, lq, d), dtype),
        interpret=interpret,
    )


def _chunked_reference(q, k, v, vl, causal: bool, scale: float):
    """Pure-jnp online-softmax attention, chunked over KV blocks with
    lax.scan — numerically identical to the kernel (same masks, same
    dead-row semantics) and DIFFERENTIABLE.  The custom VJP below runs
    the Pallas kernel forward and differentiates THIS formulation
    backward, so training never materializes the (Lq, Lk) score matrix
    either (per-step residuals are O(Lq·D·Lk/BLOCK_K))."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    bh, lq, d = q.shape
    lk = k.shape[1]
    pad = (-lk) % BLOCK_K
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0)))
    nk = k.shape[1] // BLOCK_K
    qf = q.astype(jnp.float32) * scale
    kb = k.astype(jnp.float32).reshape(bh, nk, BLOCK_K, d)
    vb = v.astype(jnp.float32).reshape(bh, nk, BLOCK_K, d)
    q_idx = jnp.arange(lq)
    vl_eff = jnp.minimum(vl.astype(jnp.float32), jnp.float32(lk))  # (bh,)

    # remat: without checkpointing, vjp-of-scan stacks each step's p
    # (bh, Lq, BLOCK_K) — a full probability matrix across steps; with it,
    # backward recomputes per-block and stores only the carries
    # (O(Lq·(D+2)·nk))
    @jax.checkpoint
    def step(carry, blk):
        m, l, acc = carry
        k_blk, v_blk, ki = blk
        s = jnp.einsum("bqd,bkd->bqk", qf, k_blk)
        k_ids = ki * BLOCK_K + jnp.arange(BLOCK_K)
        kmask = (k_ids[None, :].astype(jnp.float32)
                 < vl_eff[:, None])[:, None, :]        # (bh, 1, BK)
        mask = kmask
        if causal:
            mask = mask & (k_ids[None, None, :] <=
                           q_idx[None, :, None] + (lk - lq))
        s = jnp.where(mask, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        dead = m_new <= (_NEG_INF * 0.5)
        p = jnp.where(dead[..., None],
                      jnp.broadcast_to(kmask.astype(jnp.float32),
                                       p.shape), p)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum(
            "bqk,bkd->bqd", p, v_blk)
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((bh, lq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bh, lq), jnp.float32)
    a0 = jnp.zeros((bh, lq, d), jnp.float32)
    blks = (jnp.moveaxis(kb, 1, 0), jnp.moveaxis(vb, 1, 0),
            jnp.arange(nk))
    (m, l, acc), _ = lax.scan(step, (m0, l0, a0), blks)
    l = jnp.where(l == 0.0, 1.0, l)
    return (acc / l[..., None]).astype(q.dtype)


@functools.lru_cache(maxsize=1)
def _flash_core_fn():
    """Module-singleton custom-VJP core (built lazily so importing this
    module never imports jax)."""
    import jax

    @functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
    def core(q, k, v, vl, causal, scale, interpret):
        return _run_kernel(q, k, v, vl, causal, scale, interpret)

    def core_fwd(q, k, v, vl, causal, scale, interpret):
        return _run_kernel(q, k, v, vl, causal, scale, interpret), \
            (q, k, v, vl)

    def core_bwd(causal, scale, interpret, res, g):
        q, k, v, vl = res
        _, vjp = jax.vjp(
            lambda a, b, c: _chunked_reference(a, b, c, vl, causal, scale),
            q, k, v)
        dq, dk, dv = vjp(g)
        import jax.numpy as jnp
        return dq, dk, dv, jnp.zeros_like(vl)   # vl is a mask, not a weight
    core.defvjp(core_fwd, core_bwd)
    return core


def _flash_core(q, k, v, vl, causal: bool, scale: float, interpret: bool):
    return _flash_core_fn()(q, k, v, vl, causal, scale, interpret)


def _run_kernel(q, k, v, vl, causal: bool, scale: float, interpret: bool):
    import jax.numpy as jnp

    bh, lq, d = q.shape
    lk = k.shape[1]

    def pad_to(x, axis, mult):
        n = x.shape[axis]
        pad = (-n) % mult
        if pad == 0:
            return x
        widths = [(0, 0)] * x.ndim
        widths[axis] = (0, pad)
        return jnp.pad(x, widths)

    qp = pad_to(pad_to(q, 1, BLOCK_Q), 2, 128)
    kp = pad_to(pad_to(k, 1, BLOCK_K), 2, 128)
    vp = pad_to(pad_to(v, 1, BLOCK_K), 2, 128)
    call = _build_call(bh, qp.shape[1], kp.shape[1], qp.shape[2], lq, lk,
                       bool(causal), float(scale),
                       jnp.result_type(q).name, bool(interpret))
    return call(qp, kp, vp, vl.astype(jnp.float32))[:, :lq, :d]


def flash_attention(q, k, v, causal: bool = False, scale=None,
                    interpret=None, valid_len=None):
    """Tiled attention: softmax(scale·QKᵀ + mask)V without materializing
    the score matrix.

    Accepts (B, H, L, D) or (BH, L, D); Lq/Lk/D are padded internally to
    tile multiples (K padding is masked exactly, never approximated).
    ``valid_len`` enables per-sequence key-padding masks — shape (B,) or
    (B*H,); keys at positions >= valid_len[i] are masked exactly like the
    additive -1e9 padding mask of the XLA path.
    DIFFERENTIABLE: the forward runs the Pallas kernel, the backward
    differentiates an equivalent chunked jnp formulation — gradients also
    never touch an (Lq, Lk) score matrix.
    """
    import jax.numpy as jnp

    squeeze4 = q.ndim == 4
    if squeeze4:
        b, h, lq, dd = q.shape
        q = q.reshape(b * h, lq, dd)
        k = k.reshape(b * h, k.shape[2], dd)
        v = v.reshape(b * h, v.shape[2], dd)
    bh, lq, d = q.shape
    lk = k.shape[1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = _interpret(q)
    if valid_len is None:
        vl = jnp.full((bh,), lk, jnp.float32)
    else:
        vl = jnp.asarray(valid_len).reshape(-1).astype(jnp.float32)
        if vl.shape[0] != bh:
            if bh % vl.shape[0]:
                raise ValueError(
                    f"valid_len length {vl.shape[0]} does not divide "
                    f"batch*heads {bh}")
            vl = jnp.repeat(vl, bh // vl.shape[0])

    out = _flash_core(q, k, v, vl, bool(causal), float(scale),
                      bool(interpret))
    if squeeze4:
        out = out.reshape(b, h, lq, d)
    return out
