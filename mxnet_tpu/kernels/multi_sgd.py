"""Fused multi-tensor SGD apply as a Pallas TPU kernel.

Reference parity: src/operator/optimizer_op.cc multi_sgd_update /
multi_sgd_mom_update (multi-tensor apply, SURVEY.md §2.2 optimizer_op row)
— one kernel launch updates EVERY parameter, instead of one launch per
parameter.  The reference needs this because a ResNet has ~160 small
params whose per-kernel launch overhead dominates; on TPU the same tail
of small HBM round-trips motivates the same fusion.

TPU-native design: all tensors are flattened, each padded to a whole
number of (8, 128) fp32 tiles, and concatenated into ONE flat buffer.
The grid walks chunks of shape (8, 128); each chunk's learning rate and
weight decay are scalar-prefetched from SMEM (per-chunk arrays built on
the host once per signature), so the VPU inner loop is a single FMA pass:

    out = w - lr_chunk * (clip(g * rescale) + wd_chunk * w)

Padding guarantees a chunk never spans two tensors.  The momentum variant
carries a second state buffer through the same grid.
"""
from __future__ import annotations

import functools
from typing import Sequence, Tuple

# one grid step processes this many elements: a full fp32 VREG tile
_LANES = 128
_SUBLANES = 8
_CHUNK = _LANES * _SUBLANES


def _plan(shapes: Tuple[Tuple[int, ...], ...]):
    """Chunk layout for a tensor list: (chunks_per_tensor, total_chunks)."""
    chunks = tuple(max(1, -(-_size(s) // _CHUNK)) for s in shapes)
    return chunks, sum(chunks)


def _size(shape) -> int:
    n = 1
    for s in shape:
        n *= int(s)
    return n


@functools.lru_cache(maxsize=None)
def _jnp_dual(clip: float, dtype_name: str, momentum: float | None):
    """The kernel's jnp twin over the same packed (rows, 128) buffers.

    Off-TPU production path: interpret-mode Pallas executes the kernel
    grid step-by-step in Python (~30 ms per trainer step measured on the
    host bench — 10x the whole rest of the step), which is a TESTING
    vehicle, not a CPU backend.  XLA:CPU compiles this dual to the same
    math.  Kernel-semantics tests opt back into real interpret mode with
    MXNET_PALLAS_INTERPRET=1."""
    import jax
    import jax.numpy as jnp

    def _rowwise(lr_c, wd_c, like):
        lr = jnp.repeat(lr_c, _SUBLANES)[:, None].astype(like.dtype)
        wd = jnp.repeat(wd_c, _SUBLANES)[:, None].astype(like.dtype)
        return lr, wd

    if momentum is None:
        @jax.jit
        def sgd(lr_c, wd_c, w, g):
            lr, wd = _rowwise(lr_c, wd_c, w)
            if clip > 0:
                g = jnp.clip(g, -clip, clip)
            return w - lr * (g + wd * w)
        return sgd

    @jax.jit
    def sgd_mom(lr_c, wd_c, w, g, m):
        lr, wd = _rowwise(lr_c, wd_c, w)
        if clip > 0:
            g = jnp.clip(g, -clip, clip)
        mom_new = momentum * m - lr * (g + wd * w)
        return w + mom_new, mom_new
    return sgd_mom


def _build_call(n_chunks: int, clip: float, dtype_name: str,
                momentum: float | None, interpret: bool):
    # env resolved OUTSIDE the cache so a test's monkeypatched
    # MXNET_PALLAS_INTERPRET takes effect regardless of call order
    from ..base import get_env
    if interpret and not get_env("MXNET_PALLAS_INTERPRET"):
        return _jnp_dual(clip, dtype_name, momentum)
    return _build_pallas(n_chunks, clip, dtype_name, momentum, interpret)


@functools.lru_cache(maxsize=None)
def _build_pallas(n_chunks: int, clip: float, dtype_name: str,
                  momentum: float | None, interpret: bool):
    # rescale_grad is deliberately NOT part of this key: it changes with
    # batch size, and each new key would mean a fresh Mosaic compile.
    # The caller pre-scales the gradient instead (XLA fuses that multiply
    # into the pack reshape); clip then applies to the rescaled gradient,
    # matching the reference order clip(rescale * g).
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    dtype = jnp.dtype(dtype_name)

    def sgd_kernel(lr_ref, wd_ref, w_ref, g_ref, out_ref):
        i = pl.program_id(0)
        lr = lr_ref[i]
        wd = wd_ref[i]
        g = g_ref[:]
        if clip > 0:
            g = jnp.clip(g, -clip, clip)
        out_ref[:] = w_ref[:] - lr * (g + wd * w_ref[:])

    def sgd_mom_kernel(lr_ref, wd_ref, w_ref, g_ref, m_ref,
                       out_ref, mom_out_ref):
        i = pl.program_id(0)
        lr = lr_ref[i]
        wd = wd_ref[i]
        g = g_ref[:]
        if clip > 0:
            g = jnp.clip(g, -clip, clip)
        mom_new = momentum * m_ref[:] - lr * (g + wd * w_ref[:])
        mom_out_ref[:] = mom_new
        out_ref[:] = w_ref[:] + mom_new

    block = pl.BlockSpec((_SUBLANES, _LANES), lambda i, *_: (i, 0))
    shape = jax.ShapeDtypeStruct((n_chunks * _SUBLANES, _LANES), dtype)
    n_in = 2 if momentum is None else 3
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,           # lr and wd ride SMEM
        grid=(n_chunks,),
        in_specs=[block] * n_in,
        out_specs=block if momentum is None else [block, block],
    )
    if momentum is None:
        call = pl.pallas_call(
            sgd_kernel, grid_spec=grid_spec, out_shape=shape,
            interpret=interpret)
    else:
        call = pl.pallas_call(
            sgd_mom_kernel, grid_spec=grid_spec, out_shape=(shape, shape),
            interpret=interpret)
    return call


def _pack(arrays, chunks):
    """Flatten+pad each array to whole chunks; concat to (rows, 128)."""
    import jax.numpy as jnp
    flat = []
    for a, c in zip(arrays, chunks):
        v = jnp.ravel(a)
        pad = c * _CHUNK - v.size
        if pad:
            v = jnp.concatenate([v, jnp.zeros((pad,), v.dtype)])
        flat.append(v)
    return jnp.concatenate(flat).reshape(-1, _LANES)


def _unpack(buf, shapes, chunks):
    import jax.numpy as jnp
    out = []
    offset = 0
    flat = jnp.ravel(buf)
    for s, c in zip(shapes, chunks):
        n = _size(s)
        out.append(flat[offset:offset + n].reshape(s))
        offset += c * _CHUNK
    return out


def _per_chunk(values, chunks, dtype):
    # values may be a traced array (LR schedules must not retrigger
    # compilation); chunks is always a static tuple, so repeat is traceable
    import jax.numpy as jnp
    return jnp.repeat(jnp.asarray(values, dtype), jnp.asarray(chunks),
                      total_repeat_length=sum(chunks))


def _interpret(example=None) -> bool:
    """Interpret mode off-TPU.  Decided by where the DATA lives, not the
    default backend: a live TPU backend with CPU-resident arrays would
    otherwise hand Mosaic a CPU lowering (which pallas rejects).

    Only meaningful on EAGER calls — under jit ``example`` is a tracer
    with no device and this falls back to the default backend; traced
    callers (the registered multi_sgd ops) must pass the decision in as
    the explicit static ``interpret`` kwarg instead."""
    import jax
    if example is not None:
        try:
            dev = getattr(example, "device", None)
            dev = dev() if callable(dev) else dev
            if dev is None:
                devs = example.devices()
                dev = next(iter(devs))
            return dev.platform not in ("tpu", "axon")
        except Exception:
            pass
    return jax.default_backend() == "cpu"


def fused_multi_sgd(weights: Sequence, grads: Sequence,
                    lrs, wds, rescale_grad: float = 1.0,
                    clip_gradient: float = -1.0, interpret=None):
    """One Pallas launch updating every (weight, grad) pair.

    ``lrs``/``wds`` are per-tensor sequences OR traced arrays (LR
    schedules therefore never retrigger compilation).  Returns the list
    of updated weights (same shapes/dtypes).
    """
    import jax.numpy as jnp
    shapes = tuple(tuple(w.shape) for w in weights)
    chunks, n_chunks = _plan(shapes)
    dtype = jnp.result_type(weights[0])
    if interpret is None:
        interpret = _interpret(weights[0])
    call = _build_call(n_chunks, float(clip_gradient),
                       dtype.name, None, bool(interpret))
    lr_c = _per_chunk(lrs, chunks, dtype)
    wd_c = _per_chunk(wds, chunks, dtype)
    w_buf = _pack(weights, chunks)
    g_buf = _pack([g * rescale_grad for g in grads], chunks)
    out = call(lr_c, wd_c, w_buf, g_buf)
    return _unpack(out, shapes, chunks)


def fused_multi_sgd_mom(weights: Sequence, grads: Sequence, moms: Sequence,
                        lrs, wds, momentum: float = 0.9,
                        rescale_grad: float = 1.0,
                        clip_gradient: float = -1.0, interpret=None):
    """Momentum variant; returns (updated_weights, updated_moms)."""
    import jax.numpy as jnp
    shapes = tuple(tuple(w.shape) for w in weights)
    chunks, n_chunks = _plan(shapes)
    dtype = jnp.result_type(weights[0])
    if interpret is None:
        interpret = _interpret(weights[0])
    call = _build_call(n_chunks, float(clip_gradient),
                       dtype.name, float(momentum), bool(interpret))
    lr_c = _per_chunk(lrs, chunks, dtype)
    wd_c = _per_chunk(wds, chunks, dtype)
    w_buf = _pack(weights, chunks)
    g_buf = _pack([g * rescale_grad for g in grads], chunks)
    m_buf = _pack(moms, chunks)
    w_out, m_out = call(lr_c, wd_c, w_buf, g_buf, m_buf)
    return _unpack(w_out, shapes, chunks), _unpack(m_out, shapes, chunks)
