"""In-tree Pallas TPU kernels (the ◆ native-hardening mandate, SURVEY.md
§7 M9).

Where the reference ships hand-written CUDA kernels (src/operator/*.cu),
the hot paths here that XLA fusion does not already win get hand-written
Pallas kernels compiled by Mosaic for the TPU's VPU/MXU.  Every kernel
also runs under the Pallas interpreter so the CPU test mesh exercises the
same code path.
"""
from .multi_sgd import fused_multi_sgd, fused_multi_sgd_mom
from .flash_attention import flash_attention

__all__ = ["fused_multi_sgd", "fused_multi_sgd_mom", "flash_attention"]
