"""mxnet_tpu: a TPU-native deep-learning framework with MXNet 1.x's
capabilities (reference: thomelane/incubator-mxnet — see SURVEY.md).

Not a port: the compute path is JAX/XLA/Pallas and parallelism is
`jax.sharding` over device meshes; the *user-facing surface* (NDArray,
autograd, Gluon, Symbol/Module, KVStore, io, metric, optimizer) mirrors the
reference so model code carries over.

Conventional entry point::

    import mxnet_tpu as mx
    x = mx.nd.ones((2, 3), ctx=mx.tpu(0))
"""
from __future__ import annotations

__version__ = "0.1.0"

from .base import MXNetError, register_env, get_env, list_env

# numerics-parity escape hatch: TPU matmuls default to bf16-precision
# accumulation (the MXU fast path); set MXNET_MATMUL_PRECISION=highest to
# force full fp32 (reference-exact numerics, ~3x slower matmuls).
# Resolved through the knob table BEFORE the first jax import below.
_prec = get_env("MXNET_MATMUL_PRECISION")
if _prec:
    import jax as _jax
    _jax.config.update("jax_default_matmul_precision", _prec)
from . import faults
from .context import Context, cpu, gpu, tpu, cpu_pinned, num_gpus, num_tpus, \
    current_context
from . import context
from . import engine
from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray
from . import autograd
from . import random
from . import initializer
from . import init
from . import optimizer
from .optimizer import Optimizer
from . import lr_scheduler
from . import metric
from . import kvstore
from . import kvstore as kv
from . import recordio
from . import sparse
ndarray.sparse = sparse          # reference surface: mx.nd.sparse
from . import io
from . import image
from . import model
from . import callback
from . import monitor
from .monitor import Monitor
from . import rnn
from . import name
from . import attribute
from .attribute import AttrScope
from . import gluon
from . import parallel
from . import symbol
from . import symbol as sym
from . import numpy as np          # the numpy-compatible frontend (mx.np)
from . import numpy_extension as npx  # DL ops for numpy-frontend code
from . import module
from . import module as mod
from . import contrib
from . import profiler
from . import runtime
from . import visualization
from . import visualization as viz
from . import operator
ndarray.Custom = operator.Custom     # reference surface: mx.nd.Custom
from . import rtc
from . import test_utils
from . import observability
from . import serving
from . import tuning
# opt-in persistent compile cache: wiring the disk tier (segment hooks
# + jax's own cache dir) costs nothing when the knob is unset
if get_env("MXTPU_COMPILE_CACHE_DIR"):
    tuning.compile_cache.active()
# opt-in exporters: a Prometheus /metrics endpoint when
# MXTPU_METRICS_PORT is set, a periodic JSONL snapshot writer when
# MXTPU_METRICS_JSONL is set; no cost (export never even imports)
# otherwise
if get_env("MXTPU_METRICS_PORT") or get_env("MXTPU_METRICS_JSONL"):
    observability.export.maybe_start_from_env()
# opt-in continuous stack sampler: a daemon folding all-thread stacks
# into rotating flamegraph windows when MXTPU_PROF_SAMPLE_HZ > 0 (the
# trainer/server constructors re-probe, so late env changes also take;
# unset = the sampler module never even imports here)
if get_env("MXTPU_PROF_SAMPLE_HZ"):
    observability.sampler.maybe_start_from_env()


def waitall() -> None:
    """Block until all queued computation finishes (reference: mx.nd.waitall)."""
    engine.wait_all()
