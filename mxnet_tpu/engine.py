"""Dispatch engine: ordering + synchronization over JAX's async runtime.

Reference role: src/engine/ — the threaded dependency engine that serializes
conflicting reads/writes of NDArray variables and runs everything async
(SURVEY.md §2.1, "the heart of MXNet's async-everything model").

TPU-native design: XLA/PJRT *already* provides async dispatch with data-flow
ordering — every jax op returns immediately with a future-like Array, and
consumers are ordered by value dependence.  What the reference's engine adds
beyond that is (a) ordering of *mutations* (NDArray is mutable), and
(b) explicit sync points.  Mutation ordering here is achieved structurally:
an in-place op produces a *new* immutable buffer and bumps the NDArray's
version, so conflicting writes are serialized by the GIL-ordered version
update rather than by a scheduler (see ndarray.py).  This module therefore
carries the *interface*: engine-type selection (NaiveEngine = force-sync for
debugging, exactly the reference's MXNET_ENGINE_TYPE escape hatch), sync
points (wait_for_var / wait_all), and a bulk/dispatch-statistics hook used by
the profiler.
"""
from __future__ import annotations

import os
import threading
from typing import Any

from .base import get_env

__all__ = ["Engine", "engine", "is_naive", "wait_all"]


class Engine:
    """Process-wide engine singleton (interface-compatible with the reference's
    ``Engine::Get()``)."""

    _inst = None
    _lock = threading.Lock()

    def __init__(self):
        self._type = os.environ.get("MXNET_ENGINE_TYPE",
                                    "ThreadedEnginePerDevice")
        self._num_ops = 0
        # profiler hooks: fn(op_name, outputs, dispatch_us)
        self._listeners = []

    @classmethod
    def get(cls) -> "Engine":
        with cls._lock:
            if cls._inst is None:
                cls._inst = Engine()
            return cls._inst

    # -- mode --------------------------------------------------------------
    @property
    def engine_type(self) -> str:
        return self._type

    def set_engine_type(self, name: str) -> None:
        self._type = name

    @property
    def is_naive(self) -> bool:
        return self._type == "NaiveEngine"

    # -- dispatch hooks ----------------------------------------------------
    def on_push(self, op_name: str, outputs: Any,
                dispatch_us: float = 0.0) -> None:
        """Called by the invoke path after dispatching an op; dispatch_us
        is the measured host-side dispatch latency (async — device time is
        the XLA trace's job, as it was the CUDA profiler's in the
        reference).

        In NaiveEngine mode, block until the results are ready — the direct
        analog of the reference's synchronous debug engine.
        """
        self._num_ops += 1
        for fn in self._listeners:
            fn(op_name, outputs, dispatch_us)
        if self.is_naive:
            import jax
            jax.block_until_ready(outputs)

    def add_listener(self, fn) -> None:
        self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        if fn in self._listeners:
            self._listeners.remove(fn)

    @property
    def num_ops_dispatched(self) -> int:
        return self._num_ops

    # -- sync points -------------------------------------------------------
    def wait_for_var(self, data) -> None:
        """Block until a value is computed (reference: Engine::WaitForVar)."""
        import jax
        jax.block_until_ready(data)

    def wait_all(self) -> None:
        """Block until all outstanding computation completes
        (reference: Engine::WaitForAll / MXNDArrayWaitAll).

        Runtime errors raised by async computation surface HERE, exactly
        as in the reference engine.  Only errors that mean "this buffer no
        longer exists" (deleted/donated while we iterate the live list —
        an expected race) are suppressed.
        """
        import jax
        for arr in jax.live_arrays():
            try:
                arr.block_until_ready()
            except (RuntimeError, ValueError) as e:
                msg = str(e).lower()
                if "deleted" in msg or "donated" in msg:
                    continue  # buffer went away mid-iteration: not an error
                raise


def engine() -> Engine:
    return Engine.get()


def is_naive() -> bool:
    return Engine.get().is_naive


def wait_all() -> None:
    Engine.get().wait_all()
