"""Dispatch engine: ordering + synchronization over JAX's async runtime.

Reference role: src/engine/ — the threaded dependency engine that serializes
conflicting reads/writes of NDArray variables and runs everything async
(SURVEY.md §2.1, "the heart of MXNet's async-everything model").

TPU-native design: XLA/PJRT *already* provides async dispatch with data-flow
ordering — every jax op returns immediately with a future-like Array, and
consumers are ordered by value dependence.  What the reference's engine adds
beyond that is (a) ordering of *mutations* (NDArray is mutable), and
(b) explicit sync points.  Mutation ordering here is achieved structurally:
an in-place op produces a *new* immutable buffer and bumps the NDArray's
version, so conflicting writes are serialized by the GIL-ordered version
update rather than by a scheduler (see ndarray.py).  This module therefore
carries the *interface*: engine-type selection (NaiveEngine = force-sync for
debugging, exactly the reference's MXNET_ENGINE_TYPE escape hatch), sync
points (wait_for_var / wait_all), and the bulk/dispatch-statistics surface.

Bulked dispatch (reference: MXNET_EXEC_BULK_EXEC_TRAIN, the "bulking" of
consecutive engine pushes into one dispatch): the imperative invoke path in
ndarray/register.py defers fusable ops into a lazy segment instead of
executing them one XLA dispatch at a time, and flushes the whole segment as
ONE jitted fused executable at a sync point.  This module owns the knobs
(bulk on/off, MXNET_ENGINE_BULK_SIZE cap, NaiveEngine forces flush-per-op),
the counters (``Engine.stats()``), and the flush hook the sync points call
— the segment builder itself lives next to the invoke path it serves.
"""
from __future__ import annotations

import os
import threading
from typing import Any

from .base import get_env, hot_path
from .observability.registry import registry as _metrics_registry

__all__ = ["Engine", "engine", "is_naive", "wait_all", "PendingValue"]


class PendingValue:
    """Placeholder living in ``NDArray._data`` while the producing op sits
    in an unflushed bulk segment (the 'pending write var' of the reference
    engine).  ``segment`` is the owning segment (duck-typed: needs only
    ``.flush()`` and ``.error``), ``index`` its slot in the segment's flat
    output tuple.  NDArray._read() treats this type as the barrier: any
    read materializes the whole segment first."""

    __slots__ = ("segment", "index")

    def __init__(self, segment, index: int):
        self.segment = segment
        self.index = index


# Installed by ndarray.register at import time; called by the sync points
# below so `engine` never has to import the frontend layer (which imports
# this module).  The hook flushes the CALLING thread's pending segment.
_flush_hook = None


def _install_flush_hook(fn) -> None:
    global _flush_hook
    _flush_hook = fn


@hot_path("dispatch")
def flush_pending() -> None:
    """Flush the calling thread's pending bulk segment, if any."""
    if _flush_hook is not None:
        _flush_hook()


# os.environ's decoded-bytes dict, when the platform exposes it: the bulk
# knobs are re-read on EVERY op dispatch (live toggling is part of the
# env-var contract), and os.environ.get's key encode costs ~1µs — real
# money on a ~6µs defer path.  Falls back to os.environ.get elsewhere.
# posix-only: on Windows os.environ._data is str-keyed (and upper-cased),
# so bytes lookups would silently always miss.
_ENV_DATA = getattr(os.environ, "_data", None) if os.name == "posix" \
    else None
if not isinstance(_ENV_DATA, dict):
    _ENV_DATA = None


def _raw_env(key_bytes: bytes, key_str: str):
    if _ENV_DATA is not None:
        return _ENV_DATA.get(key_bytes)
    return os.environ.get(key_str)


class Engine:
    """Process-wide engine singleton (interface-compatible with the reference's
    ``Engine::Get()``)."""

    _inst = None
    _lock = threading.Lock()

    def __init__(self):
        # singleton __init__: runs once per process, after which
        # engine() is a plain attribute read
        # mxlint: disable=hot-path-purity — one-time singleton init
        self._type = get_env("MXNET_ENGINE_TYPE")
        # profiler hooks: fn(op_name, outputs, dispatch_us)
        self._listeners = []
        # bulk_enabled memo: (raw env string, parsed bool) — the invoke
        # hot path asks once per op, so a full get_env parse each time
        # showed up in profiles; os.environ.get + string compare doesn't
        self._bulk_raw = object()
        self._bulk_parsed = True
        self._fuse_raw = object()
        self._fuse_parsed = "exact"
        # dispatch/bulking counters live in the process-global metrics
        # registry (mxnet_tpu.observability) under `engine.*`; stats()
        # below is a thin back-compat view.  Hot paths bump `.n` directly
        # — the same plain int add the former attributes were.
        reg = _metrics_registry()
        self._c_dispatched = reg.counter(
            "engine.ops_dispatched",
            help="per-op XLA dispatches (unbulked path)")
        self._c_bulked = reg.counter(
            "engine.ops_bulked",
            help="ops deferred into fused bulk segments")
        self._c_segments = reg.counter(
            "engine.segments_flushed",
            help="bulk segments executed as one fused dispatch")
        self._c_bulked_flushed = reg.counter(
            "engine.bulked_ops_flushed",
            help="ops carried by flushed segments")
        self._c_cache_hits = reg.counter(
            "engine.segment_cache_hits",
            help="fused-executable cache hits")
        self._c_cache_misses = reg.counter(
            "engine.segment_cache_misses",
            help="fused-executable cache misses (compiles)")
        self._h_flush = reg.histogram(
            "engine.flush_us",
            help="per-segment flush latency in microseconds")

    @classmethod
    def get(cls) -> "Engine":
        with cls._lock:
            if cls._inst is None:
                cls._inst = Engine()
            return cls._inst

    # -- mode --------------------------------------------------------------
    @property
    def engine_type(self) -> str:
        return self._type

    def set_engine_type(self, name: str) -> None:
        # NaiveEngine must observe every op synchronously from the moment
        # it is selected — anything still parked in a segment flushes now
        flush_pending()
        self._type = name

    @property
    def is_naive(self) -> bool:
        return self._type == "NaiveEngine"

    # -- bulking config ----------------------------------------------------
    @property
    def bulk_enabled(self) -> bool:
        """Whether the invoke path may defer ops into fused segments.
        NaiveEngine forces flush-per-op (the reference's behavior: the
        debug engine never bulks); the env var is read live so tests and
        users can toggle at runtime, as with the reference's knobs.
        The raw value is memoized against the environ entry itself —
        this property runs once per op dispatch."""
        if self._type == "NaiveEngine":
            return False
        raw = _raw_env(b"MXNET_EXEC_BULK_EXEC_TRAIN",
                       "MXNET_EXEC_BULK_EXEC_TRAIN")
        if raw != self._bulk_raw:
            self._bulk_parsed = bool(get_env("MXNET_EXEC_BULK_EXEC_TRAIN"))
            self._bulk_raw = raw
        return self._bulk_parsed

    @property
    def bulk_size(self) -> int:
        """Max ops per segment (reference: MXNET_ENGINE_BULK_SIZE)."""
        n = get_env("MXNET_ENGINE_BULK_SIZE")
        return max(1, int(n))

    def set_bulk_size(self, n: int) -> None:
        """Set the live ``MXNET_ENGINE_BULK_SIZE`` cap — the
        BulkSizeController's apply path.  Environment-backed on purpose:
        the ``bulk_size`` property reads the knob at segment creation,
        so the new cap takes effect on the very next segment, and child
        processes (bench subprocesses, spawned workers) inherit the
        tuned value."""
        os.environ["MXNET_ENGINE_BULK_SIZE"] = str(max(1, int(n)))

    @property
    def bulk_fuse_mode(self) -> str:
        """Segment codegen mode: 'exact' (default — one dispatch per
        segment but per-op kernels, BITWISE identical to the unbulked
        path) or 'aggressive' (full XLA fusion: fastest, enables taped
        segments, allows FMA contraction ⇒ ~1-ulp drift)."""
        raw = _raw_env(b"MXNET_ENGINE_BULK_FUSE", "MXNET_ENGINE_BULK_FUSE")
        if raw != self._fuse_raw:
            v = (raw or b"exact").strip().lower()
            self._fuse_parsed = "aggressive" \
                if v in (b"aggressive", "aggressive") else "exact"
            self._fuse_raw = raw
        return self._fuse_parsed

    # -- dispatch hooks ----------------------------------------------------
    @hot_path("dispatch")
    def on_push(self, op_name: str, outputs: Any,
                dispatch_us: float = 0.0) -> None:
        """Called by the invoke path after dispatching an op; dispatch_us
        is the measured host-side dispatch latency (async — device time is
        the XLA trace's job, as it was the CUDA profiler's in the
        reference).

        In NaiveEngine mode, block until the results are ready — the direct
        analog of the reference's synchronous debug engine.
        """
        self._c_dispatched.n += 1
        for fn in self._listeners:
            fn(op_name, outputs, dispatch_us)
        if self.is_naive:
            import jax
            jax.block_until_ready(outputs)

    @hot_path("dispatch")
    def on_bulk_flush(self, n_ops: int, cache_hit,
                      flush_us: float = 0.0) -> None:
        """A segment of ``n_ops`` deferred ops executed as one fused
        dispatch.  cache_hit: True/False = the fused-executable cache was
        consulted; None = it never was (fully-dead segment, nothing ran)
        — counted in neither hits nor misses.  ``flush_us`` (measured by
        the segment builder) lands in the ``engine.flush_us`` histogram —
        the signal the MXNET_ENGINE_BULK_SIZE auto-tune follow-up needs."""
        self._c_segments.n += 1
        self._c_bulked_flushed.n += n_ops
        if cache_hit is not None:
            if cache_hit:
                self._c_cache_hits.n += 1
            else:
                self._c_cache_misses.n += 1
        self._h_flush.observe(flush_us)
        for fn in self._listeners:
            fn(f"_BulkFlush[{n_ops}]", (), flush_us)

    def add_listener(self, fn) -> None:
        """Install a dispatch listener (profiler/monitor).  Listeners
        need REAL per-op outputs, so bulking suspends while any listener
        is installed — the invoke path checks ``_listeners`` directly;
        anything already deferred flushes on its usual sync points (the
        listener then sees the ``_BulkFlush[n]`` event)."""
        self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        if fn in self._listeners:
            self._listeners.remove(fn)

    @property
    def num_ops_dispatched(self) -> int:
        return self._c_dispatched.n

    # -- statistics (the "bulk/dispatch-statistics hook") ------------------
    def stats(self) -> dict:
        """Dispatch/bulking counters — a back-compat VIEW over the
        ``engine.*`` metrics in the observability registry (one
        ``registry().snapshot()`` returns these plus every other
        subsystem's).  ``ops_dispatched`` counts per-op XLA dispatches
        (unbulked path), ``ops_bulked`` ops deferred into segments; their
        sum is every op that entered the invoke path.  Mean segment
        length is over FLUSHED segments; flush latency percentiles come
        from the ``engine.flush_us`` histogram."""
        flushed = self._c_segments.n
        flush_h = self._h_flush.read()
        return {
            "ops_dispatched": self._c_dispatched.n,
            "ops_bulked": self._c_bulked.n,
            "segments_flushed": flushed,
            "mean_segment_length": (
                round(self._c_bulked_flushed.n / flushed, 3) if flushed
                else 0.0),
            "segment_cache_hits": self._c_cache_hits.n,
            "segment_cache_misses": self._c_cache_misses.n,
            "flush_us_p50": flush_h["p50"],
            "flush_us_p99": flush_h["p99"],
        }

    def reset_stats(self) -> None:
        for m in (self._c_dispatched, self._c_bulked, self._c_segments,
                  self._c_bulked_flushed, self._c_cache_hits,
                  self._c_cache_misses, self._h_flush):
            m.reset()

    # -- sync points -------------------------------------------------------
    def wait_for_var(self, data) -> None:
        """Block until a value is computed (reference: Engine::WaitForVar).
        A pending bulk segment flushes first — WaitForVar is a sync point."""
        flush_pending()
        import jax
        if hasattr(data, "_read"):       # NDArray accepted for convenience
            data = data._read()
        jax.block_until_ready(data)

    def wait_all(self) -> None:
        """Block until all outstanding computation completes
        (reference: Engine::WaitForAll / MXNDArrayWaitAll).

        Runtime errors raised by async computation surface HERE, exactly
        as in the reference engine.  Only errors that mean "this buffer no
        longer exists" (deleted/donated while we iterate the live list —
        an expected race) are suppressed.
        """
        flush_pending()
        import jax
        for arr in jax.live_arrays():
            try:
                arr.block_until_ready()
            except (RuntimeError, ValueError) as e:
                msg = str(e).lower()
                if "deleted" in msg or "donated" in msg:
                    continue  # buffer went away mid-iteration: not an error
                raise


def engine() -> Engine:
    # lock-free fast path: the singleton never changes once created, and
    # the invoke hot path calls this per op
    inst = Engine._inst
    return inst if inst is not None else Engine.get()


def is_naive() -> bool:
    return Engine.get().is_naive


def wait_all() -> None:
    Engine.get().wait_all()
