"""``mx.init`` alias for the initializer module (reference parity)."""
from .initializer import *  # noqa: F401,F403
from .initializer import create, register  # noqa: F401
