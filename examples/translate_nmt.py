"""Train a toy seq2seq transformer and translate with beam search
(BASELINE config #4's workflow; reference: Sockeye train + translate
CLIs over the Symbol/Gluon APIs).

The toy language pairs each "word" with its mirror token; the model
learns the mapping and `translate()` decodes held-out sentences with
greedy and beam search.

    python examples/translate_nmt.py --epochs 240 --cpu
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon.model_zoo.transformer import TransformerNMT

BOS, EOS = 1, 2


def make_batch(rs, n, L, vocab):
    src = rs.randint(3, vocab, (n, L))
    tgt = vocab + 2 - src            # "mirror" language
    ti = np.concatenate([np.full((n, 1), BOS), tgt], 1)
    to = np.concatenate([tgt, np.full((n, 1), EOS)], 1)
    return src, ti, to


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=240)
    ap.add_argument("--vocab", type=int, default=12)
    ap.add_argument("--seq-len", type=int, default=5)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=5e-3)
    ap.add_argument("--beam", type=int, default=3)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    V = args.vocab + args.vocab      # source + mirrored target ids
    rs = np.random.RandomState(0)
    net = TransformerNMT(vocab_size=V + 3, num_layers=1, units=32,
                         hidden_size=64, num_heads=4, max_length=16,
                         dropout=0.0)
    net.initialize(mx.init.Xavier())
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": args.lr})
    lf = gluon.loss.SoftmaxCrossEntropyLoss()
    for step in range(args.epochs):
        src, ti, to = make_batch(rs, args.batch_size, args.seq_len,
                                 args.vocab)
        with autograd.record():
            logits = net(nd.array(src), nd.array(ti))
            L = nd.mean(lf(nd.reshape(logits, shape=(-1, V + 3)),
                           nd.reshape(nd.array(to), shape=(-1,))))
        L.backward()
        tr.step(args.batch_size)
        if step % 60 == 0:
            print(f"step {step} loss {float(L.asnumpy()):.4f}")

    src, _, _ = make_batch(rs, 4, args.seq_len, args.vocab)
    refs = (args.vocab + 2 - src).tolist()
    greedy, gscores = net.translate(nd.array(src), bos=BOS, eos=EOS,
                                    max_len=args.seq_len + 3)
    beam, bscores = net.translate(nd.array(src), bos=BOS, eos=EOS,
                                  max_len=args.seq_len + 3,
                                  beam_size=args.beam)
    tok = lambda outs: np.mean([  # noqa: E731
        o[i] == r[i] for o, r in zip(outs, refs)
        for i in range(min(len(o), len(r)))]) if outs else 0.0
    print(f"greedy token acc {tok(greedy):.3f} "
          f"scores {[round(s, 2) for s in gscores]}")
    print(f"beam-{args.beam} token acc {tok(beam):.3f} "
          f"scores {[round(s, 2) for s in bscores]}")
    ok = tok(beam) >= 0.8
    print("translation", "OK" if ok else "WEAK")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
