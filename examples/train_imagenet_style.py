"""ResNet data-parallel training from RecordIO (BASELINE config #2;
reference: example/image-classification/train_imagenet.py).

Feeds the native C++ ImageRecordIter pipeline into the whole-step-jitted
ShardedTrainer (gradients psum over the device mesh, donated params).
Point --rec at a file produced by ``python -m mxnet_tpu.tools.im2rec``;
without one, a synthetic .rec is generated.

    python examples/train_imagenet_style.py --model resnet18_v1 --epochs 2
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import parallel as par
from mxnet_tpu.gluon import loss as gloss
from mxnet_tpu.gluon.model_zoo.vision import get_model
from mxnet_tpu.io import ImageRecordIter


def ensure_rec(path, n=256, classes=10):
    if os.path.exists(path):
        return
    from mxnet_tpu.recordio import IRHeader, MXRecordIO, pack_img
    rng = np.random.default_rng(0)
    rec = MXRecordIO(path, "w")
    for i in range(n):
        img = rng.integers(0, 255, (112, 120, 3), dtype=np.uint8)
        rec.write(pack_img(IRHeader(0, float(i % classes), i, 0), img,
                           quality=85))
    rec.close()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rec", default="/tmp/example_train.rec")
    ap.add_argument("--model", default="resnet18_v1")
    ap.add_argument("--classes", type=int, default=10)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--image-size", type=int, default=96)
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()

    ensure_rec(args.rec, classes=args.classes)
    it = ImageRecordIter(
        args.rec, (3, args.image_size, args.image_size), args.batch_size,
        shuffle=True, rand_crop=True, rand_mirror=True,
        mean_r=123.68, mean_g=116.78, mean_b=103.94,
        std_r=58.4, std_g=57.12, std_b=57.38,
        preprocess_threads=os.cpu_count() or 4)

    net = get_model(args.model, classes=args.classes)
    net.initialize(mx.init.Xavier())
    tr = par.ShardedTrainer(
        net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": args.lr, "momentum": 0.9, "wd": 1e-4})

    for epoch in range(args.epochs):
        it.reset()
        t0, n = time.perf_counter(), 0
        for batch in it:
            loss = tr.step(batch.data[0], batch.label[0])
            n += batch.data[0].shape[0]
        print(f"epoch {epoch}: loss {float(loss.asnumpy()):.4f} "
              f"{n / (time.perf_counter() - t0):.1f} img/s")
    tr.sync_params()
    net.export("/tmp/example_model")
    print("exported /tmp/example_model-symbol.json + .params")


if __name__ == "__main__":
    main()
