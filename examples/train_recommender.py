"""Two-tower recommender with row-sparse embedding gradients.

The sparse-embedding fast path end to end: ``Embedding(sparse_grad=True)``
makes the backward a segment-sum over the batch's unique ids and the
optimizer a lazy gather->update->scatter over only those rows — the
whole table is never touched.  Synthetic Zipfian(1.05) id traffic (the
canonical recommender popularity skew) over a wide vocab, so each batch
touches a few percent of the table at most.

    python examples/train_recommender.py --steps 60
    python examples/train_recommender.py --dense   # dense-grad baseline
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import parallel as par
from mxnet_tpu.gluon import nn, loss as gloss
from mxnet_tpu.gluon.block import HybridBlock
from mxnet_tpu.observability.registry import registry


class TwoTower(HybridBlock):
    """User tower + item tower over one shared vocab, concat -> click
    head.  Both tables ride the sparse gradient path."""

    def __init__(self, vocab, dim, sparse_grad, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.user = nn.Embedding(vocab, dim, sparse_grad=sparse_grad)
            self.item = nn.Embedding(vocab, dim, sparse_grad=sparse_grad)
            self.user_mlp = nn.Dense(64, activation="relu")
            self.item_mlp = nn.Dense(64, activation="relu")
            self.top = nn.Dense(2)

    def hybrid_forward(self, F, x):
        u = self.user_mlp(F.flatten(
            self.user(F.slice_axis(x, axis=1, begin=0, end=1))))
        i = self.item_mlp(F.flatten(
            self.item(F.slice_axis(x, axis=1, begin=1, end=2))))
        return self.top(F.concat(u, i, dim=1))


def zipf_batch(rng, batch, vocab):
    """(user_id, item_id) pairs under Zipfian(1.05) popularity; the
    label is a synthetic click from a hidden affinity rule."""
    ids = np.minimum(rng.zipf(1.05, (batch, 2)) - 1, vocab - 1)
    y = ((ids[:, 0] + ids[:, 1]) % 3 == 0).astype(np.int64)
    return ids.astype(np.float32), y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--batch-size", type=int, default=512)
    ap.add_argument("--vocab", type=int, default=50_000)
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--dense", action="store_true",
                    help="disable sparse_grad (dense-gradient baseline)")
    args = ap.parse_args()

    mx.random.seed(7)
    rng = np.random.RandomState(11)
    net = TwoTower(args.vocab, args.dim, not args.dense, prefix="rec_")
    net.initialize(mx.init.Xavier(rnd_type="uniform"))
    tr = par.ShardedTrainer(net, gloss.SoftmaxCrossEntropyLoss(),
                            "adam", {"learning_rate": args.lr})

    t0 = time.perf_counter()
    running = None
    for step in range(1, args.steps + 1):
        x, y = zipf_batch(rng, args.batch_size, args.vocab)
        loss = float(tr.step(x, y).asnumpy())
        running = loss if running is None else 0.9 * running + 0.1 * loss
        if step % 20 == 0 or step == args.steps:
            print(f"step {step}: loss {running:.4f}")
    dt = time.perf_counter() - t0

    mode = "dense" if args.dense else "sparse"
    print(f"{mode} grads: {args.steps} steps in {dt:.2f}s "
          f"({args.steps * args.batch_size / dt:.0f} examples/s)")
    snap = registry().snapshot()
    if not args.dense:
        print(f"sparse.grad_rows: {snap.get('sparse.grad_rows', 0)} "
              f"(density {snap.get('sparse.grad_density', 0.0):.4f}); "
              f"tables touched row-wise, never densified")


if __name__ == "__main__":
    main()
