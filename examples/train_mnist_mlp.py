"""Imperative Gluon MLP training (BASELINE config #1; reference:
example/image-classification/train_mnist.py).

Runs on real handwritten-digit data (sklearn's bundled digits scans —
no download needed) or synthetic MNIST-shaped data with --synthetic.

    python examples/train_mnist_mlp.py --epochs 10
"""
import argparse
import sys
import os

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


def load_data(synthetic: bool):
    if synthetic:
        rng = np.random.RandomState(7)
        temp = rng.rand(10, 64).astype(np.float32)
        y = rng.randint(0, 10, 2000)
        X = temp[y] + 0.1 * rng.randn(2000, 64).astype(np.float32)
    else:
        from sklearn.datasets import load_digits
        X, y = load_digits(return_X_y=True)
        X = X.astype(np.float32) / 16.0
    X -= 0.5
    n = int(len(X) * 0.85)
    return (X[:n], y[:n]), (X[n:], y[n:])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=100)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--synthetic", action="store_true")
    ap.add_argument("--hybridize", action="store_true")
    args = ap.parse_args()

    (Xtr, ytr), (Xte, yte) = load_data(args.synthetic)
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(128, activation="relu"),
                gluon.nn.Dense(64, activation="relu"),
                gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())
    if args.hybridize:
        net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()

    for epoch in range(args.epochs):
        metric.reset()
        for i in range(0, len(Xtr), args.batch_size):
            x = mx.nd.array(Xtr[i:i + args.batch_size])
            y = mx.nd.array(ytr[i:i + args.batch_size])
            with autograd.record():
                out = net(x)
                L = loss_fn(out, y)
            L.backward()
            trainer.step(x.shape[0])
            metric.update([y], [out])
        test_acc = float(np.mean(np.argmax(
            net(mx.nd.array(Xte)).asnumpy(), 1) == yte))
        print(f"epoch {epoch}: train {metric.get()[1]:.4f} "
              f"test {test_acc:.4f}")


if __name__ == "__main__":
    main()
