"""Continuous-batching model serving (the inference path).

Serves a hybridized MLP through `mxnet_tpu.serving.ModelServer`: an
admission queue with backpressure, shape-bucketed batch assembly, and
one compiled CachedOp call per bucket, with concurrent client threads
offering load.  Prints p50/p99 latency, achieved QPS, and the
batch-formation efficiency the observability registry measured.

    python examples/serve_continuous_batching.py --clients 4 --requests 200

The exported-model path (the C-ABI seam documented in
examples/serve_c_api.md) serves the same way:

    net.export("model")   # model-symbol.json + model-0000.params
    srv = ModelServer.from_exported("model-symbol.json", "data",
                                    "model-0000.params")

Knobs (also settable per-constructor): MXTPU_SERVING_MAX_BATCH,
MXTPU_SERVING_QUEUE_DEPTH, MXTPU_SERVING_DEADLINE_MS,
MXTPU_SERVING_WORKERS, MXTPU_SERVING_BATCH_WINDOW_US.
"""
import argparse
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx  # noqa: F401 — backend init
from mxnet_tpu import gluon
from mxnet_tpu.observability.registry import registry
from mxnet_tpu.serving import ModelServer, ServingError


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--requests", type=int, default=200,
                    help="requests per client")
    ap.add_argument("--max-batch", type=int, default=16)
    ap.add_argument("--deadline-ms", type=float, default=0.0,
                    help="per-request deadline (0 = none)")
    args = ap.parse_args()

    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(128, activation="relu"),
                gluon.nn.Dense(64, activation="relu"),
                gluon.nn.Dense(10))
    net.initialize()
    net.hybridize()

    rng = np.random.default_rng(0)
    lat_ms, rejected = [], [0]
    lock = threading.Lock()

    def client(cid):
        crng = np.random.default_rng(cid)
        for _ in range(args.requests):
            x = crng.standard_normal((784,)).astype(np.float32)
            try:
                t0 = time.monotonic()
                y = srv.infer(x, timeout=60)
                dt = (time.monotonic() - t0) * 1e3
                assert y.shape == (10,)
                with lock:
                    lat_ms.append(dt)
            except ServingError:
                with lock:
                    rejected[0] += 1

    with ModelServer(net, max_batch=args.max_batch,
                     deadline_ms=args.deadline_ms) as srv:
        srv.warmup(rng.standard_normal((784,)).astype(np.float32))
        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(args.clients)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.monotonic() - t0

    lat_ms.sort()
    n = len(lat_ms)
    snap = registry().snapshot()
    real = snap["serving.tokens_real"]
    padded = snap["serving.tokens_padded"]   # sequence-pad positions
    slots = snap.get("serving.slots_padded", 0)
    print(f"served {n} requests from {args.clients} clients in "
          f"{wall:.2f}s ({n / wall:.0f} req/s), {rejected[0]} rejected")
    if n:
        print(f"latency p50 {lat_ms[n // 2]:.2f} ms, "
              f"p99 {lat_ms[int(n * 0.99)]:.2f} ms")
    print(f"batch efficiency {real / max(real + padded, 1):.2%} "
          f"(real / real+padded positions; {slots} padded slots)")


if __name__ == "__main__":
    main()
