"""Token-level continuous batching for generation (the decode path).

Serves a small causal transformer LM through
`mxnet_tpu.serving.GenerationServer`: one compiled prefill graph per
prompt bucket, ONE single-token decode-step graph whose carried state
is a block-managed paged KV cache, and an iteration-level scheduler —
finished generations exit the running batch at every decode step and
queued prompts take the freed slot immediately, instead of the whole
batch waiting for its slowest member.

    python examples/serve_generation.py --clients 4 --requests 24

Prints tokens/s, TTFT (time-to-first-token) p50/p99, decode-step
latency, and the KV-block occupancy the observability registry
measured (which must drain back to zero — blocks are freed on finish,
deadline expiry, and 429 alike).

Knobs (also settable per-constructor): MXTPU_SERVING_KV_BLOCK,
MXTPU_SERVING_KV_BLOCKS, MXTPU_SERVING_DECODE_SLOTS,
MXTPU_SERVING_PREFILL_MODE, MXTPU_SERVING_MAX_NEW_TOKENS.
"""
import argparse
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx  # noqa: F401 — backend init
from mxnet_tpu.gluon.model_zoo.transformer import causal_lm_small
from mxnet_tpu.observability.registry import registry
from mxnet_tpu.serving import GenerationServer, ServingError


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--requests", type=int, default=24,
                    help="generations per client")
    ap.add_argument("--slots", type=int, default=4,
                    help="decode-batch width (running generations)")
    ap.add_argument("--max-new", type=int, default=12)
    ap.add_argument("--prefill-mode", choices=("interleave", "step"),
                    default="interleave")
    args = ap.parse_args()
    os.environ["MXTPU_SERVING_PREFILL_MODE"] = args.prefill_mode

    np.random.seed(0)
    lm = causal_lm_small()
    lm.initialize()
    lm.hybridize()
    ttft_ms, tokens, rejected = [], [0], [0]
    lock = threading.Lock()

    def client(cid):
        rng = np.random.default_rng(cid)
        for _ in range(args.requests):
            plen = int(rng.integers(3, 14))
            prompt = rng.integers(1, 250, (plen,)).astype(np.int32)
            try:
                req = srv.submit_generate(prompt)
                out = req.result(timeout=60)
                with lock:
                    tokens[0] += len(out)
                    ttft_ms.append((req.t_first - req.t_enqueue) * 1e3)
            except ServingError:
                with lock:
                    rejected[0] += 1

    with GenerationServer(lm, slots=args.slots, kv_block=16,
                          kv_blocks=128, max_new_tokens=args.max_new,
                          prompt_buckets=(16,), queue_depth=256,
                          deadline_ms=0) as srv:
        srv.warmup()                # all graphs compiled up front
        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(args.clients)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.monotonic() - t0
        st = srv.stats()

    ttft_ms.sort()
    n = len(ttft_ms)
    snap = registry().snapshot()
    step = snap.get("serving.decode_step_us", {})
    print(f"completed {st['done']} generations "
          f"({tokens[0]} tokens) from {args.clients} clients in "
          f"{wall:.2f}s = {tokens[0] / wall:.1f} tokens/s, "
          f"{rejected[0]} rejected")
    if n:
        print(f"TTFT p50 {ttft_ms[n // 2]:.2f} ms, "
              f"p99 {ttft_ms[min(n - 1, int(n * 0.99))]:.2f} ms")
    if step.get("count"):
        print(f"decode steps {st['decode_steps']} "
              f"(mean {step['mean']:.0f} us/step, p99 "
              f"{step['p99']:.0f} us, batch width {st['slots']})")
    print(f"KV blocks used after drain: {st['kv_blocks_used']} "
          f"of {st['kv_blocks_total']} (must be 0)")


if __name__ == "__main__":
    main()
