"""The multi-model production frontend, end to end over a real socket.

Serves TWO models from one process through
`mxnet_tpu.serving.HttpFrontend` — a predict model (JSON in/out over
`POST /v1/models/<name>/predict`) and a small causal LM streaming
tokens over Server-Sent Events (`POST /v1/models/<name>/generate`) —
then exercises the whole wire surface with stdlib HTTP clients:

1. readiness + the registry listing (`/readyz`, `/v1/models`);
2. concurrent JSON predict clients (responses bitwise-match what
   `submit()` returns in-process);
3. SSE generation with socket-measured TTFT;
4. a rolling blue/green weight swap while predict traffic is live
   (zero dropped requests — every response is old weights or new,
   never torn);
5. priority shedding: the registry gate 429s the low-priority model
   while the high-priority one keeps flowing;
6. graceful shutdown draining every model.

    python examples/serve_http.py --clients 4 --requests 12

Knobs: MXTPU_FRONTEND_PORT (deployment port; this example binds
ephemeral), MXTPU_FRONTEND_PRIORITY, MXTPU_FRONTEND_SLO_MS.
"""
import argparse
import http.client
import json
import os
import socket
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx  # noqa: F401 — backend init
from mxnet_tpu import gluon
from mxnet_tpu.gluon.model_zoo.transformer import causal_lm_small
from mxnet_tpu.serving import (GenerationServer, HttpFrontend,
                               ModelRegistry, ModelServer)


class Scale2(gluon.HybridBlock):
    def hybrid_forward(self, F, x):
        return F.tanh(x * 2.0) + 0.5


class Scale3(gluon.HybridBlock):
    """The 'green' weights for the blue/green swap demo."""

    def hybrid_forward(self, F, x):
        return F.tanh(x * 3.0) - 0.25


def _block(cls):
    net = cls()
    net.initialize()
    net.hybridize()
    return net


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--requests", type=int, default=12,
                    help="predict requests per client")
    ap.add_argument("--generations", type=int, default=3)
    ap.add_argument("--max-new", type=int, default=8)
    args = ap.parse_args()

    np.random.seed(0)
    mx.random.seed(0)
    lm = causal_lm_small()
    lm.initialize()
    lm.hybridize()

    registry = ModelRegistry()
    predict_srv = ModelServer(_block(Scale2), max_batch=8,
                              batch_window_us=300.0)
    registry.load("scale", predict_srv, priority=1, slo_ms=50.0)
    gen_srv = GenerationServer(lm, slots=2, kv_block=16, kv_blocks=64,
                               max_new_tokens=args.max_new,
                               prompt_buckets=(16,), queue_depth=64,
                               deadline_ms=0)
    registry.load("lm", gen_srv, priority=2, slo_ms=200.0, warm=True)

    frontend = HttpFrontend(registry, port=0).start()
    port = frontend.port
    print(f"frontend listening on 127.0.0.1:{port} "
          f"({len(registry.names())} models)")

    status, body = _get(port, "/readyz")
    names = [m["name"] for m in _get(port, "/v1/models")[1]["models"]]
    print(f"readyz {status}, models: {','.join(names)}")

    # -- concurrent JSON predict --------------------------------------
    rng = np.random.default_rng(7)
    xs = [rng.uniform(-1, 1, (16,)).astype(np.float32)
          for _ in range(args.clients * args.requests)]
    direct = [predict_srv.infer(x) for x in xs]
    mismatches, errors = [0], [0]
    lock = threading.Lock()

    def client(cid):
        for i in range(cid, len(xs), args.clients):
            st, _, out = _post(port, "/v1/models/scale/predict",
                               {"inputs": [xs[i].tolist()],
                                "dtype": "float32"})
            with lock:
                if st != 200:
                    errors[0] += 1
                elif not np.array_equal(
                        np.asarray(out["outputs"][0], np.float32),
                        direct[i]):
                    mismatches[0] += 1

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(args.clients)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    print(f"predict: {len(xs)} requests from {args.clients} HTTP "
          f"clients in {wall:.2f}s, {errors[0]} errors, "
          f"{mismatches[0]} mismatches vs direct submit() "
          f"(bitwise: {'OK' if not mismatches[0] else 'FAIL'})")

    # -- SSE token streaming ------------------------------------------
    ttfts = []
    for g in range(args.generations):
        prompt = rng.integers(1, 250, (5,)).astype(np.int32)
        toks, ttft = _sse(port, "lm", prompt, args.max_new)
        ttfts.append(ttft * 1e3)
        if g == 0:
            print(f"generate: streamed {len(toks)} tokens over SSE "
                  f"{toks}")
    print(f"SSE socket TTFT: " +
          ", ".join(f"{t:.1f}ms" for t in sorted(ttfts)))

    # -- blue/green swap under live traffic ---------------------------
    x = xs[0]
    old = predict_srv.infer(x)
    stop = threading.Event()
    outs, swap_errors = [], [0]

    def swap_client():
        while not stop.is_set():
            st, _, out = _post(port, "/v1/models/scale/predict",
                               {"inputs": [x.tolist()],
                                "dtype": "float32"})
            with lock:
                if st != 200:
                    swap_errors[0] += 1
                else:
                    outs.append(np.asarray(out["outputs"][0],
                                           np.float32))

    threads = [threading.Thread(target=swap_client) for _ in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.2)
    staged = registry.swap("scale", _block(Scale3))
    time.sleep(0.2)
    stop.set()
    for t in threads:
        t.join()
    new = predict_srv.infer(x)
    n_old = sum(np.array_equal(o, old) for o in outs)
    n_new = sum(np.array_equal(o, new) for o in outs)
    print(f"blue/green swap: staged {staged} executable(s) under live "
          f"traffic; responses old={n_old} new={n_new} "
          f"torn={len(outs) - n_old - n_new} errors={swap_errors[0]} "
          f"(zero dropped: "
          f"{'OK' if not swap_errors[0] else 'FAIL'})")

    # -- priority shedding --------------------------------------------
    registry.set_shed_level(2)        # sheds priority < 2 ("scale")
    st_low = _post(port, "/v1/models/scale/predict",
                   {"inputs": [x.tolist()], "dtype": "float32"})[0]
    st_high = _post(port, "/v1/models/lm/generate",
                    {"prompt": [3, 5], "max_new_tokens": 2},
                    stream=False)[0]
    registry.set_shed_level(0)
    print(f"shedding at level 2: low-priority predict -> {st_low}, "
          f"high-priority generate -> {st_high}")

    frontend.stop(drain=True)
    print(f"frontend drained; KV blocks used: "
          f"{gen_srv.stats()['kv_blocks_used']} (must be 0)")


def _get(port, path):
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        c.request("GET", path)
        r = c.getresponse()
        return r.status, json.loads(r.read())
    finally:
        c.close()


def _post(port, path, obj, stream=True):
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    try:
        c.request("POST", path, body=json.dumps(obj))
        r = c.getresponse()
        body = r.read()
        try:
            parsed = json.loads(body)
        except ValueError:
            parsed = {}               # SSE body
        return r.status, dict(r.getheaders()), parsed
    finally:
        c.close()


def _sse(port, name, prompt, max_new):
    body = json.dumps({"prompt": [int(t) for t in prompt],
                       "max_new_tokens": max_new})
    s = socket.create_connection(("127.0.0.1", port), timeout=60)
    try:
        t0 = time.monotonic()
        s.sendall((f"POST /v1/models/{name}/generate HTTP/1.1\r\n"
                   f"Host: x\r\nContent-Length: {len(body)}\r\n\r\n"
                   f"{body}").encode())
        buf, ttft = b"", None
        while True:
            chunk = s.recv(65536)
            if ttft is None and b"data:" in buf + chunk:
                ttft = time.monotonic() - t0
            if not chunk:
                break
            buf += chunk
    finally:
        s.close()
    toks = [json.loads(line.partition(b":")[2])["token"]
            for line in buf.split(b"\n")
            if line.startswith(b"data:") and b'"token"' in line]
    return toks, ttft


if __name__ == "__main__":
    main()
