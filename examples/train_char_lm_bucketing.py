"""Bucketed language-model training with the legacy mx.rnn cell API
(BASELINE config #4's workflow; reference: example/rnn/bucketing/
lstm_bucketing.py).

Variable-length token sequences bucket into a few padded lengths; the
BucketingModule compiles ONE XLA executable per bucket (jit cache per
shape — SURVEY.md §5.7) over a stacked LSTM built with
mx.rnn.LSTMCell.unroll.

    python examples/train_char_lm_bucketing.py --epochs 8
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import rnn

CORPUS = (
    "the quick brown fox jumps over the lazy dog . "
    "a stitch in time saves nine . "
    "all that glitters is not gold . "
    "actions speak louder than words . "
) * 4


def build_data(batch_size, buckets):
    words = CORPUS.split()
    rng = np.random.RandomState(0)
    sents = []
    for i in range(0, len(words) - max(buckets), 2):
        L = int(rng.choice(buckets))
        sents.append(words[i:i + L])
    coded, vocab = rnn.encode_sentences(sents, invalid_label=0,
                                        start_label=1)
    it = rnn.BucketSentenceIter(coded, batch_size, buckets=buckets,
                                invalid_label=0)
    return it, len(vocab) + 1


def sym_gen_factory(vocab_size, emb_dim, hidden, num_layers):
    def sym_gen(seq_len):
        data = mx.sym.var("data")
        label = mx.sym.var("softmax_label")
        embed = mx.sym.Embedding(data, input_dim=vocab_size,
                                 output_dim=emb_dim, name="embed")
        stack = rnn.SequentialRNNCell()
        for i in range(num_layers):
            stack.add(rnn.LSTMCell(hidden, prefix=f"lstm_l{i}_"))
        outputs, _ = stack.unroll(seq_len, embed, layout="NTC",
                                  merge_outputs=True)
        pred = mx.sym.reshape(outputs, shape=(-1, hidden))
        pred = mx.sym.FullyConnected(pred, num_hidden=vocab_size,
                                     name="pred")
        label = mx.sym.reshape(label, shape=(-1,))
        pred = mx.sym.SoftmaxOutput(pred, label, name="softmax")
        return pred, ("data",), ("softmax_label",)
    return sym_gen


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=4)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--emb", type=int, default=16)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--lr", type=float, default=0.03)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    buckets = [4, 6]
    it, vocab_size = build_data(args.batch_size, buckets)
    print(f"vocab={vocab_size} buckets={buckets} "
          f"default={it.default_bucket_key}")

    mod = mx.mod.BucketingModule(
        sym_gen_factory(vocab_size, args.emb, args.hidden, args.layers),
        default_bucket_key=it.default_bucket_key,
        context=mx.context.cpu())
    metric = mx.metric.Perplexity(invalid_label=0)
    mod.fit(it, num_epoch=args.epochs, eval_metric=metric,
            optimizer="adam",
            optimizer_params={"learning_rate": args.lr},
            batch_end_callback=mx.callback.Speedometer(
                args.batch_size, frequent=50))
    score = dict(mod.score(it, mx.metric.Perplexity(invalid_label=0)))
    print(f"final perplexity: {score['perplexity']:.3f}")
    return 0 if score["perplexity"] < float(vocab_size) else 1


if __name__ == "__main__":
    sys.exit(main())
