"""BERT pretraining example: the real masked-LM + NSP objective through
the sharded trainer, with optional flash attention.

Mirrors the round-4 bench config #3 as a user-facing recipe:
  - 15% of (valid) tokens masked; labels are the original ids; the loss
    is CE over masked positions plus the NSP head's CE
  - padding arrives as (B,) valid LENGTHS (the GluonNLP valid_length
    idiom) so the Pallas flash kernel can mask per row even under jit
  - the whole train step is ONE jitted computation (ShardedTrainer);
    on a multi-chip mesh the same script shards dp x tp x sp

Run (synthetic data, tiny model):
  python examples/pretrain_bert_mlm.py --steps 20
  MXNET_USE_FLASH_ATTENTION=1 python examples/pretrain_bert_mlm.py
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import parallel as par
from mxnet_tpu.gluon.model_zoo.transformer import bert_base, bert_small

MASK_ID = 3


def mlm_nsp_loss(out, ys):
    mlm, nsp = out
    labels, weights, nsp_y = ys
    logp = nd.log_softmax(mlm, axis=-1)
    ce = -nd.pick(logp, labels, axis=-1)
    mlm_l = nd.sum(ce * weights) / nd.sum(weights)
    nsp_logp = nd.log_softmax(nsp, axis=-1)
    return mlm_l - nd.mean(nd.pick(nsp_logp, nsp_y, axis=-1))


def synthetic_batch(rng, batch, seq, vocab):
    tokens = rng.integers(4, vocab, (batch, seq))
    valid_lens = rng.integers(seq // 2, seq + 1, (batch,))
    valid = np.arange(seq)[None, :] < valid_lens[:, None]
    mask_pos = (rng.random((batch, seq)) < 0.15) & valid
    mask_pos[:, 1] = True
    inputs = np.where(mask_pos, MASK_ID, tokens)
    segs = np.zeros((batch, seq), np.int64)
    nsp_y = rng.integers(0, 2, (batch,))
    x = (inputs, segs, valid_lens.astype(np.float32))
    y = (tokens, mask_pos.astype(np.float32), nsp_y)
    return x, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--full-size", action="store_true",
                    help="bert_base instead of the tiny test config")
    args = ap.parse_args()

    import jax
    n_dev = len(jax.devices())
    batch = max(args.batch, n_dev) // n_dev * n_dev   # dp-shardable
    vocab = 30522 if args.full_size else 1000
    net = (bert_base if args.full_size else bert_small)(dropout=0.0)
    net.initialize()
    tr = par.ShardedTrainer(net, mlm_nsp_loss, "adam",
                            {"learning_rate": 3e-3})
    rng = np.random.default_rng(0)
    x, y = synthetic_batch(rng, batch, args.seq, vocab)
    for step in range(args.steps):
        loss = tr.step(x, y, batch_size=1)
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step}: mlm+nsp loss {float(loss.asnumpy()):.4f}",
                  flush=True)


if __name__ == "__main__":
    main()
