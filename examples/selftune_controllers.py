"""Self-tuning runtime: controller configuration (the config snippet).

Arms the feedback-controller layer over a small imperative training
loop: the BulkSizeController hill-climbs the live
``MXNET_ENGINE_BULK_SIZE`` cap from the ``engine.flush_us`` histogram
the loop itself produces, while the prefetch controller watches the
loader gauge.  Demonstrates the three configuration surfaces:

1. **stock, knob-gated** — ``tuning.start()`` arms all four standard
   controllers; ``MXTPU_TUNE_*`` env knobs enable/disable each one and
   ``MXTPU_TUNE_DRY_RUN=1`` turns the whole layer into an observer;
2. **custom rails** — construct controllers yourself with explicit
   guard rails / hysteresis and pass them to ``tuning.start``;
3. **synchronous ticks** — skip the timer thread entirely and call
   ``runtime().tick_all()`` at your own cadence (what this script does,
   so the demo is deterministic and prints each decision).

Pair with ``MXTPU_COMPILE_CACHE_DIR=/path`` to also persist every
compiled executable across restarts (the second run of this script
then performs ~0 recompiles — watch ``tuning.compiles``).

    python examples/selftune_controllers.py --steps 8 --cpu
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=8,
                    help="controller ticks (one workload slice each)")
    ap.add_argument("--ops", type=int, default=400,
                    help="fusable ops dispatched per slice")
    ap.add_argument("--dry-run", action="store_true",
                    help="record decisions, apply nothing")
    ap.add_argument("--cpu", action="store_true",
                    help="force the virtual CPU mesh (CI smoke mode)")
    args = ap.parse_args()

    if args.cpu:
        from mxnet_tpu.base import force_cpu_mesh
        force_cpu_mesh(1)

    import mxnet_tpu as mx  # noqa: F401 — backend init
    from mxnet_tpu import nd, tuning
    from mxnet_tpu.observability.registry import registry

    # -- configuration surface 2: custom rails --------------------------
    controllers = [
        tuning.BulkSizeController(
            vmin=4, vmax=48,          # guard rails for this host class
            min_segments=4,           # decide on few segments (demo)
            hysteresis=1,
            enabled=True,             # bypass the MXTPU_TUNE_BULK knob
            dry_run=args.dry_run),
        tuning.PrefetchController(
            initial=4, vmax=32, enabled=True, dry_run=args.dry_run),
    ]
    rt = tuning.runtime()
    for c in controllers:
        rt.add(c)
    # configuration surface 1 would instead be just:  tuning.start()
    # (stock controllers, every one gated by its MXTPU_TUNE_* knob)

    def slice_of_work():
        """One workload slice: a chain of fusable elementwise ops —
        each chain becomes bulk segments capped at the LIVE bulk
        size, feeding the engine.flush_us histogram the controller
        steers on."""
        x = nd.ones((256, 256))
        y = x
        for _ in range(args.ops):
            y = y * 1.0001 + 0.0001
        return float(y.asnumpy()[0, 0])   # sync point: flush

    print(f"{'tick':>4} {'bulk':>5} {'flush p50us':>12} "
          f"{'decision':<60}")
    for t in range(args.steps):
        slice_of_work()
        # -- configuration surface 3: synchronous ticks ----------------
        decisions = rt.tick_all()
        bulk = os.environ.get("MXNET_ENGINE_BULK_SIZE", "15")
        p50 = registry().snapshot()["engine.flush_us"]["p50"]
        what = "; ".join(
            f"{d['controller']}: {d['from']:g}->{d['to']:g}"
            f"{'' if d['applied'] else ' (dry-run/held)'}"
            for d in decisions) or "-"
        print(f"{t:>4} {bulk:>5} {p50:>12.1f} {what:<60}")

    snap = registry().snapshot()
    print(f"\ndecisions={snap.get('tuning.bulk_size.decisions', 0)} "
          f"applied={snap.get('tuning.bulk_size.applied', 0)} "
          f"clamped={snap.get('tuning.bulk_size.clamped', 0)} "
          f"converged_bulk={os.environ.get('MXNET_ENGINE_BULK_SIZE')}")
    print("flight tuning ring:",
          len(__import__('mxnet_tpu').observability.flight.recorder()
              .tunings()), "decision record(s)")
    print("SELFTUNE_EXAMPLE_OK")


if __name__ == "__main__":
    main()
