"""Long-context classification with banded (Longformer-style) attention.

The sliding-window op trio keeps attention O(L*w): this script trains a
2-layer banded encoder on sequences of length 2048 — a dense encoder's
(L, L) score matrices at this length would dominate memory — and shows
the two long-context tools side by side:

- single chip: `LongformerEncoder` (this file) — banded attention;
- multi chip:  sequence parallelism over the `sp` mesh axis
  (`parallel/ring.py`, see tests/test_parallel.py) — dense attention
  sharded over devices.

Run (CPU or TPU):  python examples/train_longformer_longctx.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import gluon, nd
from mxnet_tpu import parallel as par
from mxnet_tpu.gluon.model_zoo.transformer import LongformerEncoder

VOCAB, BATCH, CLASSES = 256, 8, 4   # batch divides any dp mesh
SEQ = 2048          # overridable via --seq


def synthetic_batch(rng, seq):
    """Label = which quadrant of the sequence holds the marker token —
    solvable only if information propagates across the band."""
    tokens = rng.integers(2, VOCAB, (BATCH, seq))
    labels = rng.integers(0, CLASSES, (BATCH,))
    q = seq // CLASSES
    for b, lab in enumerate(labels):
        pos = rng.integers(lab * q, (lab + 1) * q)
        tokens[b, pos] = 1                      # the marker
    return tokens.astype(np.int64), labels


def main(steps=30, seq=SEQ):
    mx.random.seed(0)
    rng = np.random.default_rng(0)
    enc = LongformerEncoder(VOCAB, num_layers=2, units=64,
                            hidden_size=128, num_heads=4,
                            w=max(8, seq // 32),
                            dilation=(1, 1, 2, 4),  # mixed receptive field
                            max_length=seq)
    enc.initialize(mx.init.Xavier())
    head = gluon.nn.Dense(CLASSES)
    head.initialize(mx.init.Xavier())

    class Model(gluon.Block):
        def forward(self, tokens):
            h = enc(tokens)                     # (B, L, U), O(L*w) attn
            return head(nd.max(h, axis=1))

        def collect_params(self, select=None):
            p = enc.collect_params(select)
            p.update(head.collect_params(select))
            return p

    trainer = par.ShardedTrainer(
        Model(), gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": 1e-3})
    for step in range(steps):
        tokens, labels = synthetic_batch(rng, seq)
        loss = trainer.step(tokens, labels)
        if step % 5 == 0 or step == steps - 1:
            print(f"step {step:3d}  loss {float(loss.asnumpy()):.4f}")


if __name__ == "__main__":
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--seq", type=int, default=SEQ)
    a = ap.parse_args()
    main(steps=a.steps, seq=a.seq)
    print("done")
