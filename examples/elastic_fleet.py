"""Elastic fleet demo: survive a host loss mid-run, no operator action.

Launches a small localhost fleet (default 3 worker processes joined
through one JAX coordination service), trains a toy MLP data-parallel
(each worker on its own ``num_shards="dist"`` batch stripe), and
SIGKILLs one worker mid-run via the deterministic fault plan
(``host_loss@<step>``).  The survivors then:

1. detect the dead host within one lease TTL (heartbeat leases over the
   coordination-service KV store — ``parallel/membership.py``),
2. quiesce at the next step boundary and run the KV consensus re-form
   (view exchange → plan → acks → committed fence bump),
3. re-install the process group at the reduced world size with
   contiguous ranks, purge the dead host's KV generations,
4. restore the last committed checkpoint, re-wind the loader onto the
   new shard assignment, and keep training to the target step.

Run::

    python examples/elastic_fleet.py            # 3 workers, kill rank 2
    python examples/elastic_fleet.py --workers 3 --kill-rank 2 \
        --kill-step 5 --target 10

Each surviving worker prints its re-form line and final state; the
launcher prints the merged timeline and ``ELASTIC_EXAMPLE_OK``.
"""
import argparse
import os
import socket
import subprocess
import sys
import textwrap

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""
    import json, os, sys, time
    sys.path.insert(0, os.environ["MXNET_TEST_ROOT"])
    from mxnet_tpu.base import force_cpu_mesh
    force_cpu_mesh(1, verify=False)   # distributed init precedes the
    import numpy as np                # first backend query
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn, loss as gloss
    from mxnet_tpu.gluon.data import DataLoader
    from mxnet_tpu.parallel import (dist, FleetReformed, HostFenced,
                                    ResilientTrainer, ShardedTrainer)
    from mxnet_tpu.parallel.mesh import make_mesh
    from mxnet_tpu.observability.flight import recorder

    dist.init_process_group()          # MXTPU_ELASTIC=1 set by launcher
    phys = dist.phys_rank()
    TARGET = int(os.environ["ELASTIC_TARGET_T"])
    ckpt_dir = os.path.join(os.environ["ELASTIC_CKPT_ROOT"],
                            "rank%d" % phys)

    N, F, C = 256, 8, 4
    def sample(i):
        x = ((np.arange(F) * 7 + i * 13) % 97).astype(np.float32) / 97.0
        return x, np.int32(i % C)
    loader = DataLoader([sample(i) for i in range(N)], batch_size=8,
                        num_shards="dist")

    mx.random.seed(11)
    np.random.seed(11)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", in_units=F))
        net.add(nn.Dense(C, in_units=16))
    net.initialize()
    trainer = ShardedTrainer(
        net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9},
        mesh=make_mesh({"dp": 1}, devices=jax.local_devices()[:1]))
    rt = ResilientTrainer(trainer, checkpoint_dir=ckpt_dir,
                          checkpoint_every=2, elastic=True,
                          loader=loader, skip_nonfinite=False)

    done = False
    while not done:
        try:
            for x, y in loader:
                loss = rt.step(x, y)
                if trainer.num_update >= TARGET:
                    done = True
                    break
        except FleetReformed as e:
            r = e.result
            print("rank %d: fleet re-formed at generation %d — lost %s, "
                  "world %d -> %d, resumed from step %s" %
                  (phys, r.fence, list(r.dead), len(r.old_members),
                   r.new_world, r.resumed_t), flush=True)
            continue
        except HostFenced:
            print("rank %d: fenced out (false death) — exiting" % phys,
                  flush=True)
            sys.exit(3)

    rt.flush()
    events = [m.get("event") for m in recorder().memberships()]
    loss_val = float(np.asarray(jax.device_get(loss._read())))
    print("rank %d: done at step %d (loss %.4f; membership timeline: %s)"
          % (phys, trainer.num_update, loss_val, " -> ".join(events)),
          flush=True)
    dist.barrier("elastic_example_done", timeout=60)
    print("WORKER_%d_DONE" % phys, flush=True)
""")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--workers", type=int, default=3)
    ap.add_argument("--kill-rank", type=int, default=2,
                    help="rank that dies (host_loss fault; SIGKILL)")
    ap.add_argument("--kill-step", type=int, default=5)
    ap.add_argument("--target", type=int, default=10,
                    help="train until this update counter")
    ap.add_argument("--workdir", default=None,
                    help="checkpoint root (default: a temp dir)")
    args = ap.parse_args()
    if not 0 <= args.kill_rank < args.workers:
        sys.exit("--kill-rank must name one of the workers")
    if args.workers < 3:
        sys.exit("need >= 3 workers: 2 survivors must outvote the loss")

    import tempfile
    workdir = args.workdir or tempfile.mkdtemp(prefix="mxtpu_elastic_")
    port = _free_port()
    script = os.path.join(workdir, "elastic_worker.py")
    with open(script, "w") as f:
        f.write(WORKER)

    procs = []
    for r in range(args.workers):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.update({
            "MXNET_TEST_ROOT": ROOT,
            "JAX_PLATFORMS": "cpu",
            "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(port),
            "DMLC_NUM_WORKER": str(args.workers),
            "DMLC_WORKER_ID": str(r),
            # elastic mode + test-scale lease timings
            "MXTPU_ELASTIC": "1",
            "MXTPU_ELASTIC_LEASE_TTL": "1.5",
            "MXTPU_ELASTIC_HEARTBEAT": "0.3",
            "MXTPU_ELASTIC_REFORM_TIMEOUT": "45",
            "MXTPU_DIST_TIMEOUT": "20",
            "ELASTIC_TARGET_T": str(args.target),
            "ELASTIC_CKPT_ROOT": workdir,
        })
        if r == args.kill_rank:
            env["MXTPU_FAULT_PLAN"] = f"host_loss@{args.kill_step}"
        procs.append(subprocess.Popen(
            [sys.executable, script], env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))

    failed = False
    for r, p in enumerate(procs):
        out, _ = p.communicate(timeout=300)
        for line in out.splitlines():
            if line.startswith(("rank ", "WORKER_")):
                print(f"[worker {r}] {line}")
        if r == args.kill_rank:
            if p.returncode == 0:
                print(f"[launcher] worker {r} was supposed to die "
                      f"(host_loss@{args.kill_step}) but exited 0")
                failed = True
            else:
                print(f"[launcher] worker {r} killed as planned "
                      f"(rc {p.returncode})")
        elif p.returncode != 0:
            print(f"[launcher] survivor {r} FAILED (rc {p.returncode}):\n"
                  + out[-2000:])
            failed = True
    if failed:
        sys.exit(1)
    survivors = args.workers - 1
    print(f"survived host loss: {survivors} of {args.workers} workers "
          f"re-formed and reached step {args.target}")
    print("ELASTIC_EXAMPLE_OK")


if __name__ == "__main__":
    main()
