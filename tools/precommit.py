#!/usr/bin/env python3
"""Repo precommit gate: mxlint over the files this commit touches.

Runs ``mxlint --changed --fix --dry-run`` — lints only git-touched
``.py`` files against the frozen baseline, and shows (without applying)
any pending mechanical fixes.  Exit nonzero blocks the commit when
there are NEW findings or pending fixes; run

    python -m mxnet_tpu.tools.mxlint --changed --fix

to apply the fixes, then re-stage.

Install as a git hook (one line)::

    printf '#!/bin/sh\\nexec python tools/precommit.py\\n' \\
        > .git/hooks/pre-commit && chmod +x .git/hooks/pre-commit
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from mxnet_tpu.tools import mxlint  # noqa: E402


def main() -> int:
    rc = mxlint.main(["--changed", "--fix", "--dry-run"])
    if rc != 0:
        print("precommit: mxlint gate failed — fix the findings above "
              "(or apply pending rewrites with "
              "`python -m mxnet_tpu.tools.mxlint --changed --fix`)",
              file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
