#!/usr/bin/env python3
"""Repo precommit gate: mxlint over this commit, in two stages.

Stage 1 — ``mxlint --changed --fix --dry-run``: lints only git-touched
``.py`` files against the frozen baseline and shows (without applying)
any pending mechanical fixes.  Fast, file-local, catches the lexical
rules.

Stage 2 — a full repo run.  The flow-sensitive tier's interprocedural
halves (a blocking call reached two files down while a lock is held, a
callee that never releases a span handed to it, a class thread whose
only reader lives in another method) build their call graph from the
WHOLE project — ``--changed`` alone would judge the touched files
against a truncated graph and miss exactly the cross-file findings the
CFG tier exists for.  The full two-pass+CFG run is budgeted under 5s
(test-enforced), cheap enough for a hook.

Exit nonzero blocks the commit when either stage finds NEW findings or
pending fixes; run

    python -m mxnet_tpu.tools.mxlint --changed --fix

to apply the fixes, then re-stage.

Install as a git hook (one line)::

    printf '#!/bin/sh\\nexec python tools/precommit.py\\n' \\
        > .git/hooks/pre-commit && chmod +x .git/hooks/pre-commit
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from mxnet_tpu.tools import mxlint  # noqa: E402


def main() -> int:
    rc = mxlint.main(["--changed", "--fix", "--dry-run"])
    if rc != 0:
        print("precommit: mxlint gate failed — fix the findings above "
              "(or apply pending rewrites with "
              "`python -m mxnet_tpu.tools.mxlint --changed --fix`)",
              file=sys.stderr)
        return rc
    rc = mxlint.main([])
    if rc != 0:
        print("precommit: repo-wide mxlint gate failed — the touched "
              "files changed an interprocedural fact (call chain, "
              "held-lock set, ownership transfer) that surfaces a "
              "finding elsewhere; the hops/reason chains above point "
              "at the path", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
